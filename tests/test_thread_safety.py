"""Thread-safety analysis suite (ISSUE 9): the `thread-safety` /
`raw-lock` lint rules (every checker proven to FIRE and to stay QUIET),
the Eraser-style runtime lockset sanitizer (state machine, refinement,
init-then-publish, rlock reentry), the deterministic two-thread race
repro with its crash bundle, and the threaded admission path running
clean under the sanitizer (`make race`).
"""

import json
import os
import textwrap
import threading

import pytest

from stellar_core_tpu.lint import all_rules, rules_by_id, run_paths
from stellar_core_tpu.util import lockorder, racetrace
from stellar_core_tpu.util.racetrace import DataRaceError, race_checked


def lint_src(tmp_path, relpath, src, rule_ids=None):
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    rules = rules_by_id(rule_ids) if rule_ids else all_rules()
    return run_paths([str(tmp_path)], rules, root=str(tmp_path))


def rule_hits(report, rule_id):
    return [v for v in report.violations if v.rule == rule_id]


# ---------------------------------------------------------------------------
# static layer: thread-safety rule
# ---------------------------------------------------------------------------

class TestThreadSafetyRule:
    SHARED_UNGUARDED = """
        import threading

        class Server:
            def __init__(self):
                self.jobs = []

            def start(self):
                threading.Thread(target=self._worker, name="worker").start()

            def _worker(self):
                self.jobs.append(1)

            def on_main(self):
                self.jobs.pop()
        """

    def test_fires_on_unguarded_shared_container_mutation(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", self.SHARED_UNGUARDED,
                       ["thread-safety"])
        hits = rule_hits(rep, "thread-safety")
        assert len(hits) == 2            # the worker write and the main pop
        assert "Server.jobs" in hits[0].message
        assert "main" in hits[0].message and "worker" in hits[0].message

    def test_quiet_when_guarded(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import threading
            from util.lockorder import make_lock

            class Server:
                def __init__(self):
                    self._lock = make_lock("server.jobs")
                    self.jobs = []

                def start(self):
                    threading.Thread(target=self._worker,
                                     name="worker").start()

                def _worker(self):
                    with self._lock:
                        self.jobs.append(1)

                def on_main(self):
                    with self._lock:
                        self.jobs.pop()
            """, ["thread-safety"])
        assert not rule_hits(rep, "thread-safety")

    def test_quiet_with_owned_annotation_and_fires_without_reason(
            self, tmp_path):
        annotated = self.SHARED_UNGUARDED.replace(
            "self.jobs = []",
            "self.jobs = []  # corelint: owned-by=worker -- handoff is "
            "join()-ordered")
        rep = lint_src(tmp_path, "pkg/mod.py", annotated, ["thread-safety"])
        assert not rule_hits(rep, "thread-safety")
        # an attestation without a reason is itself a finding
        bare = self.SHARED_UNGUARDED.replace(
            "self.jobs = []", "self.jobs = []  # corelint: owned-by=worker")
        rep = lint_src(tmp_path, "pkg/mod.py", bare, ["thread-safety"])
        hits = rule_hits(rep, "thread-safety")
        assert any("needs a reason" in v.message for v in hits)

    def test_init_then_publish_fields_exempt(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import threading

            class Server:
                def __init__(self):
                    self.config = {"a": 1}     # written ONLY here

                def start(self):
                    threading.Thread(target=self._worker,
                                     name="worker").start()

                def _worker(self):
                    return self.config["a"]    # cross-thread READ is fine

                def on_main(self):
                    return self.config
            """, ["thread-safety"])
        assert not rule_hits(rep, "thread-safety")

    def test_entry_point_through_closure(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import threading

            class Server:
                def __init__(self):
                    self.jobs = []

                def start(self):
                    def run():
                        self.jobs.append(1)
                    threading.Thread(target=run, name="worker").start()

                def on_main(self):
                    self.jobs.pop()
            """, ["thread-safety"])
        hits = rule_hits(rep, "thread-safety")
        assert hits and "worker" in hits[0].message

    def test_entry_point_through_functools_partial(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import functools
            import threading

            class Server:
                def __init__(self):
                    self.jobs = []

                def start(self):
                    threading.Thread(
                        target=functools.partial(self._worker, 1),
                        name="worker").start()

                def _worker(self, n):
                    self.jobs.append(n)

                def on_main(self):
                    self.jobs.pop()
            """, ["thread-safety"])
        assert rule_hits(rep, "thread-safety")

    def test_post_action_callback_runs_on_main(self, tmp_path):
        # a callback REGISTERED from anywhere runs on the crank loop:
        # main+main is one role, so no finding — re-rooting is what keeps
        # the marshalled http_admin mutation path quiet
        rep = lint_src(tmp_path, "pkg/mod.py", """
            class Server:
                def __init__(self, clock):
                    self.clock = clock
                    self.jobs = []

                def enqueue(self):
                    def work():
                        self.jobs.append(1)
                    self.clock.post_action(work, name="w")

                def on_main(self):
                    self.jobs.pop()
            """, ["thread-safety"])
        assert not rule_hits(rep, "thread-safety")

    def test_http_handler_methods_are_entry_points(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            from http.server import BaseHTTPRequestHandler

            class Admin:
                def __init__(self):
                    self.hits = []

                def make(self):
                    admin_self = self

                    class Handler(BaseHTTPRequestHandler):
                        def do_GET(self):
                            admin_self.touch()
                    return Handler

                def touch(self):
                    self.hits.append(1)

                def on_main(self):
                    self.hits.pop()
            """, ["thread-safety"])
        hits = rule_hits(rep, "thread-safety")
        assert hits and "http-admin" in hits[0].message

    def test_thread_only_field_is_quiet(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import threading

            class Server:
                def __init__(self):
                    self.count = 0

                def start(self):
                    threading.Thread(target=self._worker,
                                     name="worker").start()

                def _worker(self):
                    self.count += 1      # only the worker role touches it
            """, ["thread-safety"])
        assert not rule_hits(rep, "thread-safety")

    def test_suppression_roundtrip(self, tmp_path):
        suppressed = self.SHARED_UNGUARDED.replace(
            "self.jobs.append(1)",
            "self.jobs.append(1)  # corelint: disable=thread-safety "
            "-- test")
        rep = lint_src(tmp_path, "pkg/mod.py", suppressed,
                       ["thread-safety"])
        assert len(rule_hits(rep, "thread-safety")) == 1   # pop still fires
        assert any(v.rule == "thread-safety" for v in rep.suppressed)


class TestRawLockRule:
    def test_fires_on_raw_lock_and_rlock(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import threading
            a = threading.Lock()
            b = threading.RLock()
            """, ["raw-lock"])
        assert len(rule_hits(rep, "raw-lock")) == 2

    def test_fires_on_aliased_from_import(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            from threading import Lock as L
            a = L()
            """, ["raw-lock"])
        assert len(rule_hits(rep, "raw-lock")) == 1

    def test_quiet_in_lockorder_and_for_make_lock(self, tmp_path):
        rep = lint_src(tmp_path, "stellar_core_tpu/util/lockorder.py", """
            import threading
            def make_lock(name):
                return threading.Lock()
            """, ["raw-lock"])
        assert not rule_hits(rep, "raw-lock")
        rep = lint_src(tmp_path, "pkg/mod.py", """
            from util.lockorder import make_lock
            a = make_lock("x")
            """, ["raw-lock"])
        assert not rule_hits(rep, "raw-lock")


# ---------------------------------------------------------------------------
# runtime layer: the lockset sanitizer
# ---------------------------------------------------------------------------

def run_in_thread(fn, name="t2"):
    """Run fn on a fresh thread; returns (result, exception)."""
    box = {}

    def wrap():
        try:
            box["r"] = fn()
        except BaseException as e:
            box["e"] = e

    t = threading.Thread(target=wrap, name=name)
    t.start()
    t.join(10.0)
    assert not t.is_alive()
    return box.get("r"), box.get("e")


@pytest.fixture
def tracing():
    """Sanitizer on for the test, prior state restored after — under
    `make race` (STPU_RACE_TRACE=1) tracing is already on process-wide
    and MUST stay on for the tests that follow."""
    prev_race = racetrace.enabled()
    prev_lock = lockorder.enabled()
    racetrace.enable()
    yield
    if not prev_race:
        racetrace.disable()
    if not prev_lock:
        lockorder.disable()


@race_checked
class Box:
    def __init__(self, guard=None):
        self._lock = guard or lockorder.make_lock("test.box")
        self.x = 0


class TestRaceSanitizer:
    def test_deterministic_two_thread_race_repro(self, tracing,
                                                 tmp_path, monkeypatch):
        """THE acceptance repro: an unguarded cross-thread write raises
        DataRaceError and writes a crash bundle naming the field; the
        same write under the shared lock passes."""
        monkeypatch.setenv("STPU_CRASH_DIR", str(tmp_path))
        b = Box()
        b.x = 1                               # owner (main) writes freely
        _, err = run_in_thread(lambda: setattr(b, "x", 2), name="racer")
        assert isinstance(err, DataRaceError)
        assert "Box.x" in str(err) and "racer" in str(err)
        bundles = [f for f in os.listdir(tmp_path)
                   if f.startswith("flight-")]
        assert bundles, "crash bundle must be written before the raise"
        doc = json.load(open(os.path.join(tmp_path, bundles[0])))
        assert "DataRaceError" in doc["reason"]
        assert "Box.x" in doc["reason"]

        # guard in place -> no race (same write, same threads)
        g = Box()
        with g._lock:
            g.x = 1

        def guarded_write():
            with g._lock:
                g.x = 2
        _, err = run_in_thread(guarded_write)
        assert err is None
        assert racetrace.field_state(g, "x")["lockset"] == ["test.box"]

    def test_init_then_publish_no_false_positive(self, tracing):
        b = Box()
        for i in range(10):
            b.x = i                  # exclusive: no lockset obligation
        lk = lockorder.make_lock("test.reader")

        def read_guarded():
            with lk:
                return b.x
        _, err = run_in_thread(read_guarded)
        assert err is None
        # a later OWNER write is not fail-stopped (monitoring pattern:
        # gauge reads from admin threads against main-owned state)
        b.x = 99
        st = racetrace.field_state(b, "x")
        assert st["state"] == "shared-modified"

    def test_lockset_refinement_to_intersection(self, tracing):
        b = Box()
        b.x = 1
        la = lockorder.make_lock("test.a")
        lb = lockorder.make_lock("test.b")

        def w_ab():
            with la, lb:
                b.x = 2
        _, err = run_in_thread(w_ab, "t-ab")
        assert err is None
        assert racetrace.field_state(b, "x")["lockset"] == \
            ["test.a", "test.b"]

        def w_b():
            with lb:
                b.x = 3
        _, err = run_in_thread(w_b, "t-b")
        assert err is None
        assert racetrace.field_state(b, "x")["lockset"] == ["test.b"]

        def w_a():                   # disjoint: lockset shrinks to empty
            with la:
                b.x = 4
        _, err = run_in_thread(w_a, "t-a")
        assert isinstance(err, DataRaceError)
        assert "lockset history" in str(err)

    def test_rlock_reentry_keeps_lockset(self, tracing):
        rl = lockorder.make_rlock("test.re")
        b = Box(guard=rl)
        with rl:
            b.x = 1

        def reentrant_write():
            with rl:
                with rl:             # re-entry must not empty the lockset
                    b.x = 2
            assert not lockorder.held_locks()
        _, err = run_in_thread(reentrant_write)
        assert err is None
        assert racetrace.field_state(b, "x")["lockset"] == ["test.re"]

    def test_ignore_param_excludes_field(self, tracing):
        @race_checked(ignore=("scratch",))
        class Scratchy:
            def __init__(self):
                self.scratch = 0
        s = Scratchy()
        s.scratch = 1
        _, err = run_in_thread(lambda: setattr(s, "scratch", 2))
        assert err is None
        assert racetrace.field_state(s, "scratch") is None

    def test_zero_overhead_when_off(self):
        if racetrace.enabled():
            pytest.skip("process-wide tracing on (make race)")

        @race_checked
        class Plain:
            pass
        # decoration while off leaves the class COMPLETELY unchanged
        assert "__setattr__" not in Plain.__dict__
        assert "__getattribute__" not in Plain.__dict__

    def test_enable_instruments_disable_restores(self):
        if racetrace.enabled():
            pytest.skip("process-wide tracing on (make race)")

        @race_checked
        class Latent:
            pass
        prev_lock = lockorder.enabled()
        racetrace.enable()
        try:
            assert "__setattr__" in Latent.__dict__
            assert "__setattr__" in Box.__dict__
        finally:
            racetrace.disable()
            if not prev_lock:
                lockorder.disable()
        assert "__setattr__" not in Latent.__dict__
        assert "__setattr__" not in Box.__dict__


# ---------------------------------------------------------------------------
# the threaded admission path under the sanitizer (`make race` shape)
# ---------------------------------------------------------------------------

class TestThreadedAdmissionUnderSanitizer:
    def test_http_style_marshalled_submissions_race_clean(self, tracing):
        """Worker threads submit through the clock's action queue (the
        http_admin marshalling pattern) while polling monitoring state
        directly (the gauge pattern), main cranks: the decorated
        TransactionQueue/AdmissionPipeline must come out race-clean —
        this is the positive control proving the ownership annotations,
        with the sanitizer ACTIVE (deterministic repro above proves it
        would have fired)."""
        from stellar_core_tpu import xdr as X
        from stellar_core_tpu.crypto.keys import SecretKey
        from stellar_core_tpu.crypto.sha import sha256
        from stellar_core_tpu.herder.admission import AdmissionPipeline
        from stellar_core_tpu.herder.tx_queue import TransactionQueue
        from stellar_core_tpu.ledger.manager import LedgerManager
        from stellar_core_tpu.testutils import (TestAccount, build_tx,
                                                create_account_op,
                                                native_payment_op)
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock

        lm = LedgerManager(sha256(b"race soak net"))
        lm.start_new_ledger()
        root_sk = lm.root_account_secret()
        e = lm.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                root_sk.public_key.ed25519))).to_xdr())
        root = TestAccount(lm, root_sk, e.data.value.seqNum)
        sks = [SecretKey(bytes([i + 1]) * 32) for i in range(8)]
        lm.close_ledger(
            [root.tx([create_account_op(
                X.AccountID.ed25519(sk.public_key.ed25519), 10**11)
                for sk in sks])],
            close_time=lm.lcl_header.scpValue.closeTime + 5)
        accts = []
        for sk in sks:
            ent = lm.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
                accountID=X.AccountID.ed25519(
                    sk.public_key.ed25519))).to_xdr())
            accts.append(TestAccount(lm, sk, ent.data.value.seqNum))

        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        clock.crank_for(1.0)
        q = TransactionQueue(lm)
        adm = AdmissionPipeline(q, lm, clock)
        verdicts = []
        frames = [a.tx([native_payment_op(accts[(i + 1) % 8].account_id,
                                          1000)])
                  for i, a in enumerate(accts)]
        done = threading.Event()
        errors = []

        def http_worker():
            try:
                for f in frames:
                    clock.post_action(
                        lambda f=f: verdicts.append(adm.submit(f)),
                        name="http-tx")
                    _ = adm.depth        # gauge-style cross-thread reads
                    _ = q.size
            except BaseException as e:
                errors.append(e)
            finally:
                done.set()

        t = threading.Thread(target=http_worker, name="http-admin")
        t.start()
        for _ in range(2000):
            clock.crank()
            if done.is_set() and len(verdicts) == len(frames):
                break
        t.join(10.0)
        adm.drain()
        adm.close()
        assert not errors, errors
        assert len(verdicts) == len(frames)
        assert all(v.code == "pending" for v in verdicts), verdicts


class TestSanitizerEdgeBehavior:
    def test_reenable_reowns_stale_state_no_false_positive(self):
        """Review fix: ownership that legitimately moved while tracing
        was OFF must not produce a DataRaceError after re-enable — each
        enable() starts a fresh epoch that re-owns stale field state."""
        if racetrace.enabled():
            pytest.skip("process-wide tracing on (make race)")
        prev_lock = lockorder.enabled()
        racetrace.enable()
        try:
            b = Box()
            b.x = 1                  # owned by main, epoch N
            racetrace.disable()
            # join()-ordered handoff while the sanitizer is off
            _, err = run_in_thread(lambda: setattr(b, "x", 2), "newowner")
            assert err is None
            racetrace.enable()       # epoch N+1

            def new_owner_writes():
                b.x = 3              # stale EXCLUSIVE(main) must re-own
            _, err = run_in_thread(new_owner_writes, "newowner")
            assert err is None
            assert racetrace.field_state(b, "x")["owner"] == "newowner"
        finally:
            racetrace.disable()
            if not prev_lock:
                lockorder.disable()

    def test_history_keeps_newest_entries_including_the_race(self, tracing):
        b = Box()
        b.x = 0
        lk = lockorder.make_lock("test.hist")

        def hammer():
            for _ in range(30):      # far past the history cap
                with lk:
                    b.x += 1
        _, err = run_in_thread(hammer, "hammerer")
        assert err is None

        def racing_write():
            b.x = -1                 # no lock: the race
        _, err = run_in_thread(racing_write, "racer")
        assert isinstance(err, DataRaceError)
        hist = racetrace.field_state(b, "x")["history"]
        # the racing access itself must be the newest retained entry
        assert hist[-1]["thread"] == "racer"
        assert hist[-1]["lockset"] == []


class TestResolutionPrecision:
    def test_bare_name_call_never_resolves_to_a_method(self, tmp_path):
        """Review fix: class methods are class attributes, not lexical
        names — a bare `process()` call from a thread body must resolve
        to the module function, never to a same-named method of an
        unrelated class (which fabricated cross-thread reach)."""
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import threading

            def process():
                return 1

            class Q:
                def __init__(self):
                    self.shared = []

                def process(self):
                    self.shared.append(1)     # main-only

                def on_main(self):
                    self.shared.pop()

            class Spawner:
                def start(self):
                    def run():
                        process()             # the MODULE function
                    threading.Thread(target=run, name="worker").start()
            """, ["thread-safety"])
        assert not rule_hits(rep, "thread-safety")

    def test_init_exemption_covers_function_nested_classes(self, tmp_path):
        """Review fix: a class defined inside a function has a qualified
        __init__ unit name ('build.__init__') — its init-then-publish
        writes must stay exempt like a module-level class's."""
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import threading

            def build():
                class Holder:
                    def __init__(self):
                        self.cfg = {"a": 1}   # written ONLY here

                    def read(self):
                        return self.cfg["a"]
                return Holder

            class Runner:
                def __init__(self):
                    self.h = None

                def start(self):
                    threading.Thread(target=self._worker,
                                     name="worker").start()

                def _worker(self):
                    self.h.read()
            """, ["thread-safety"])
        assert not rule_hits(rep, "thread-safety")
