"""Native-C corelint suite (ISSUE 15): every C rule proven to fire AND
to stay quiet on paired fixtures, the C suppression-comment grammar
round-trip (with the baseline ratchet), the brace-unbalanced parse-error
fail-stop, the whole-tree clean gate over native/*.c, and an ASan smoke
test proving the sanitizer build catches a deliberately-overflowing
decoder (skipped cleanly when cc/libasan is absent).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from stellar_core_tpu._native_build import sanitizer_available
from stellar_core_tpu.lint import (all_rules, check_baseline, run_paths,
                                   rules_by_id, write_baseline,
                                   load_baseline)
from stellar_core_tpu.lint.clex import (CFileContext, CParseError,
                                        extract_functions, tokenize)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

C_RULE_IDS = ("reader-discipline", "memcpy-provenance", "unchecked-alloc",
              "handler-result-discipline", "overlay-pairing")


def lint_c(tmp_path, src, rule_ids=None, name="native/mod.c"):
    """Write C `src` under tmp_path and lint it in isolation."""
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    rules = rules_by_id(rule_ids or C_RULE_IDS)
    return run_paths([str(tmp_path)], rules, root=str(tmp_path))


def rule_hits(report, rule_id):
    return [v for v in report.violations if v.rule == rule_id]


# ---------------------------------------------------------------------------
# lexer / function extraction
# ---------------------------------------------------------------------------

class TestClex:
    def test_tokenize_strips_comments_strings_preprocessor(self):
        toks, comments = tokenize(textwrap.dedent("""
            #include <string.h>
            /* block
               comment */
            // line comment
            static int f(void) { return "lit; }"[0] + 'x'; }
            #define M(a) \\
                (a + 1)
            """))
        texts = [t.text for t in toks]
        assert "include" not in texts          # preprocessor skipped
        assert "M" not in texts                # continuation consumed
        assert '"lit; }"' in texts             # string is ONE token
        assert "'x'" in texts
        assert len(comments) == 2
        assert "block" in comments[0][1]

    def test_function_extraction_skips_initializers_and_structs(self):
        toks, _ = tokenize(textwrap.dedent("""
            typedef struct { int a; } T;
            static const int TAB[2] = { 1, 2 };
            enum { X = 1 };
            static int
            add_one(int v)
            {
                if (v > 0) { v += 1; }
                return v;
            }
            """))
        fns = extract_functions(toks)
        assert [f.name for f in fns] == ["add_one"]
        assert [t.text for t in fns[0].params] == ["int", "v"]
        assert [t.text for t in fns[0].body[-3:]] == ["return", "v", ";"]

    def test_unbalanced_braces_raise(self):
        toks, _ = tokenize("static int f(void) { if (1) { return 0; }\n")
        with pytest.raises(CParseError):
            extract_functions(toks)

    def test_parse_error_is_reported_not_crashed(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            f(void)
            {
                return 0;
            /* missing closing brace */
            """)
        assert rep.files_scanned == 0
        assert rep.parse_errors and "mod.c" in rep.parse_errors[0]


# ---------------------------------------------------------------------------
# reader-discipline
# ---------------------------------------------------------------------------

class TestReaderDiscipline:
    def test_fires_on_raw_buffer_pointer(self, tmp_path):
        rep = lint_c(tmp_path, """
            typedef struct { const uint8_t *p; int off, len, err; } Rd;
            static int
            bad(Rd *r)
            {
                const uint8_t *q = r->p + r->off;
                return q[0];
            }
            """)
        assert len(rule_hits(rep, "reader-discipline")) == 1

    def test_fires_on_local_reader_dot_access(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            bad(const uint8_t *data, int len)
            {
                Rd r;
                rd_init(&r, data, len);
                return r.p[0];
            }
            """)
        assert len(rule_hits(rep, "reader-discipline")) == 1

    def test_quiet_via_helpers_and_inside_rd_functions(self, tmp_path):
        rep = lint_c(tmp_path, """
            static const uint8_t *
            rd_take(Rd *r, int n)
            {
                if (r->err || r->off + n > r->len) { r->err = 1; return NULL; }
                const uint8_t *q = r->p + r->off;
                r->off += n;
                return q;
            }
            static int
            good(Rd *r)
            {
                const uint8_t *q = rd_take(r, 4);
                return q != NULL && r->off < r->len;
            }
            """)
        assert not rule_hits(rep, "reader-discipline")


# ---------------------------------------------------------------------------
# memcpy-provenance
# ---------------------------------------------------------------------------

class TestMemcpyProvenance:
    def test_fires_on_unbounded_variable_length(self, tmp_path):
        rep = lint_c(tmp_path, """
            static void
            bad(uint8_t *dst, const uint8_t *src, int n)
            {
                memcpy(dst, src, n);
            }
            """)
        assert len(rule_hits(rep, "memcpy-provenance")) == 1

    def test_quiet_on_constant_sizeof_and_const_ternary(self, tmp_path):
        rep = lint_c(tmp_path, """
            static void
            good(uint8_t *dst, const uint8_t *src, int four)
            {
                memcpy(dst, src, 32);
                memcpy(dst, src, sizeof(uint64_t) * 2);
                memcpy(dst, src, four == 1 ? 4 : 12);
                memcpy(dst, src, 1 << 5);
            }
            """)
        assert not rule_hits(rep, "memcpy-provenance")

    def test_quiet_on_rd_varopaque_bound(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            good(Rd *r, uint8_t out[64])
            {
                uint32_t len;
                const uint8_t *q = rd_varopaque(r, 64, &len);
                if (!q)
                    return -1;
                memcpy(out, q, len);
                return 0;
            }
            """)
        assert not rule_hits(rep, "memcpy-provenance")

    def test_quiet_on_matching_allocation(self, tmp_path):
        rep = lint_c(tmp_path, """
            static uint8_t *
            good(const uint8_t *src, int n)
            {
                uint8_t *d = PyMem_Malloc(n);
                if (!d)
                    return NULL;
                memcpy(d, src, n);
                return d;
            }
            """)
        assert not rule_hits(rep, "memcpy-provenance")

    def test_fires_when_bound_is_in_another_function(self, tmp_path):
        # the bound must be in the SAME function: cross-function
        # provenance is exactly what the rule refuses to assume
        rep = lint_c(tmp_path, """
            static void
            sized(uint8_t *d, int n)
            {
                uint8_t *x = PyMem_Malloc(n);
                if (x)
                    d[0] = x[0];
            }
            static void
            bad(uint8_t *dst, const uint8_t *src, int n)
            {
                memcpy(dst, src, n);
            }
            """)
        assert len(rule_hits(rep, "memcpy-provenance")) == 1


# ---------------------------------------------------------------------------
# unchecked-alloc
# ---------------------------------------------------------------------------

class TestUncheckedAlloc:
    def test_fires_on_use_before_check(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            bad(int n)
            {
                int *v = PyMem_Malloc(n * sizeof(int));
                v[0] = 1;
                if (!v)
                    return -1;
                return v[0];
            }
            """)
        hits = rule_hits(rep, "unchecked-alloc")
        assert len(hits) == 1
        assert "used before a null check" in hits[0].message

    def test_fires_when_never_checked(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            bad(int n)
            {
                char *buf = malloc(n);
                buf[0] = 0;
                return 0;
            }
            """)
        assert len(rule_hits(rep, "unchecked-alloc")) == 1

    def test_quiet_on_immediate_and_combined_checks(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            good(int n, S *s)
            {
                int *a = PyMem_Malloc(n * sizeof(int));
                int *b = PyMem_Calloc(n, sizeof(int));
                if (!a || !b) {
                    PyMem_Free(a);
                    PyMem_Free(b);
                    return -1;
                }
                s->tab = PyMem_Realloc(s->tab, n * 2);
                if (s->tab == NULL)
                    return -1;
                a[0] = b[0];
                PyMem_Free(a);
                PyMem_Free(b);
                return 0;
            }
            """)
        assert not rule_hits(rep, "unchecked-alloc")

    def test_quiet_on_truthiness_guards(self, tmp_path):
        # `if (p)` / `while (p)` / ternary are null checks; `f(p)` is NOT
        rep = lint_c(tmp_path, """
            static int
            good(int n)
            {
                char *p = PyMem_Malloc(n);
                if (p)
                    p[0] = 0;
                char *q = malloc(n);
                return q ? q[0] : -1;
            }
            """)
        assert not rule_hits(rep, "unchecked-alloc")

    def test_fires_on_call_use_before_check(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            bad(int n, uint8_t *src)
            {
                char *p = PyMem_Malloc(n);
                memcpy(p, src, 4);
                if (!p)
                    return -1;
                return 0;
            }
            """)
        assert len(rule_hits(rep, "unchecked-alloc")) == 1


# ---------------------------------------------------------------------------
# handler-result-discipline
# ---------------------------------------------------------------------------

class TestHandlerResultDiscipline:
    def test_fires_on_bare_early_return(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            op_bad(Engine *e, COp *op, const uint8_t src[32], Buf *rb)
            {
                if (op == NULL)
                    return 0;
                return res_inner(rb, 1, 0) < 0 ? -1 : 1;
            }
            """)
        hits = rule_hits(rep, "handler-result-discipline")
        assert len(hits) == 1
        assert "op_bad" in hits[0].message

    def test_quiet_on_res_inner_minus_one_and_delegation(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            op_good(Engine *e, COp *op, const uint8_t src[32], Buf *rb)
            {
                if (op == NULL)
                    return res_inner(rb, 1, -1) < 0 ? -1 : 0;
                if (e == NULL)
                    return -1;
                int rc = side_effect(e, rb, src);
                if (rc <= 0)
                    return rc;
                return store_thing(e, src, rb, 6);
            }
            """)
        assert not rule_hits(rep, "handler-result-discipline")

    def test_quiet_on_success_arm_write_then_return_one(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            op_good(Engine *e, COp *op, const uint8_t src[32], Buf *rb)
            {
                if (op == NULL)
                    return -1;
                if (buf_i32(rb, 0) < 0 || buf_i64(rb, 7) < 0)
                    return -1;
                return 1;
            }
            """)
        assert not rule_hits(rep, "handler-result-discipline")

    def test_non_handler_functions_ignored(self, tmp_path):
        # no Buf param => not a handler; op_-prefixed alone is not enough
        rep = lint_c(tmp_path, """
            static int
            op_helperish(Engine *e)
            {
                return 0;
            }
            static int
            plain(Buf *rb)
            {
                (void)rb;
                return 0;
            }
            """)
        assert not rule_hits(rep, "handler-result-discipline")


# ---------------------------------------------------------------------------
# overlay-pairing
# ---------------------------------------------------------------------------

class TestOverlayPairing:
    def test_fires_on_leaked_push(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            bad(Engine *e, Buf *rb)
            {
                e->hop_active = 1;
                if (rb == NULL)
                    return -1;
                e->hop_active = 0;
                return 0;
            }
            """)
        hits = rule_hits(rep, "overlay-pairing")
        assert len(hits) == 1
        assert "hop_active" in hits[0].message

    def test_fires_on_leaky_loop_break_path(self, tmp_path):
        # the pop is skipped when the loop exits via break-then-return
        rep = lint_c(tmp_path, """
            static int
            bad(Engine *e, int n)
            {
                e->op_active = 1;
                for (int i = 0; i < n; i++) {
                    if (i == 3)
                        break;
                }
                return 0;
            }
            """)
        assert len(rule_hits(rep, "overlay-pairing")) == 1

    def test_quiet_on_balanced_paths_and_rollback_call(self, tmp_path):
        rep = lint_c(tmp_path, """
            static int
            good(Engine *e, Buf *rb, int n)
            {
                e->hop_active = 1;
                if (rb == NULL) {
                    e->hop_active = 0;
                    return -1;
                }
                switch (n) {
                case 0:
                    e->hop_active = 0;
                    return 0;
                default:
                    break;
                }
                eng_rollback_tx(e);
                return 0;
            }
            static int
            good2(Engine *e)
            {
                e->op_active = 1;
                e->op_active = e->hop_active = 0;
                return 0;
            }
            """)
        assert not rule_hits(rep, "overlay-pairing")

    def test_quiet_without_any_push(self, tmp_path):
        rep = lint_c(tmp_path, """
            static void
            reset(Engine *e)
            {
                e->hop_active = 0;
                e->op_active = 0;
            }
            """)
        assert not rule_hits(rep, "overlay-pairing")


# ---------------------------------------------------------------------------
# suppressions + ratchet
# ---------------------------------------------------------------------------

class TestCSuppressions:
    SRC = """
        typedef struct { const uint8_t *p; int off, len, err; } Rd;
        static int
        f(Rd *r)
        {
            const uint8_t *q = r->p + r->off; /* corelint: disable=reader-discipline -- fixture reason */
            return q[0];
        }
        """

    def test_suppression_round_trip(self, tmp_path):
        rep = lint_c(tmp_path, self.SRC)
        assert not rule_hits(rep, "reader-discipline")
        assert len(rep.suppressed) == 1
        assert rep.suppressed[0].rule == "reader-discipline"
        key = "native/mod.c:reader-discipline"
        assert rep.suppression_counts() == {key: 1}

    def test_file_level_suppression(self, tmp_path):
        src = "/* corelint: disable-file=reader-discipline -- fixture */\n" \
            + textwrap.dedent("""
            static int
            f(Rd *r)
            {
                return r->p[0];
            }
            """)
        rep = lint_c(tmp_path, src)
        assert not rule_hits(rep, "reader-discipline")
        assert len(rep.suppressed) == 1

    def test_ratchet_flags_new_c_suppression(self, tmp_path):
        rep = lint_c(tmp_path, self.SRC)
        problems = check_baseline(rep, {"suppressions": {}})
        assert len(problems) == 1
        assert "native/mod.c:reader-discipline" in problems[0]
        # and a regenerated baseline accepts it (two-way ratchet intact)
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), rep)
        assert check_baseline(rep, load_baseline(str(bl))) == []


# ---------------------------------------------------------------------------
# whole-tree gate + CLI
# ---------------------------------------------------------------------------

class TestWholeTreeNative:
    def test_native_tree_is_clean(self):
        rep = run_paths([os.path.join(REPO_ROOT, "native")],
                        rules_by_id(C_RULE_IDS), root=REPO_ROOT)
        assert rep.files_scanned >= 3
        assert rep.violations == [], \
            "\n".join(v.format() for v in rep.violations)
        assert not rep.parse_errors
        # the documented engine-idiom suppressions are present and exact
        counts = rep.suppression_counts()
        assert counts.get("native/capply.c:reader-discipline") == 4
        assert counts.get("native/capply.c:memcpy-provenance") == 1

    def test_python_rules_do_not_see_c_files(self):
        # dispatch isolation: running ONLY the Python rules over native/
        # scans the files but produces zero findings (no cross-language
        # crashes, no bogus hits)
        rep = run_paths([os.path.join(REPO_ROOT, "native")],
                        rules_by_id(["clock-discipline",
                                     "exception-hygiene"]),
                        root=REPO_ROOT)
        assert rep.files_scanned >= 3
        assert rep.violations == []
        assert not rep.parse_errors

    def test_cli_lists_c_rules(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu.lint", "--list-rules"],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert r.returncode == 0
        for rule in C_RULE_IDS:
            assert rule in r.stdout

    def test_cli_fires_on_bad_c_file(self, tmp_path):
        bad = tmp_path / "bad.c"
        bad.write_text(textwrap.dedent("""
            static int
            bad(int n)
            {
                char *b = malloc(n);
                b[0] = 0;
                return 0;
            }
            """))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu.lint", str(bad),
             "--root", str(tmp_path), "--json"],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert r.returncode == 1
        assert "unchecked-alloc" in r.stdout


# ---------------------------------------------------------------------------
# sanitizer smoke test
# ---------------------------------------------------------------------------

class TestSanitizerSmoke:
    @pytest.mark.skipif(not sanitizer_available(),
                        reason="no cc/libasan in this environment")
    def test_asan_catches_overflowing_decoder(self, tmp_path):
        """Compile a deliberately-overflowing XDR-ish decoder with the
        same flags `make native-asan` uses and prove ASan fail-stops it:
        the tier is only meaningful if a real out-of-bounds read dies."""
        from stellar_core_tpu._native_build import _SANITIZE_FLAGS, _cc
        src = tmp_path / "overflow.c"
        src.write_text(textwrap.dedent("""
            #include <stdint.h>
            #include <stdlib.h>
            #include <string.h>
            /* a decoder that trusts the wire length instead of the
               buffer bound — exactly what reader-discipline forbids */
            static int
            decode(const uint8_t *p, int wire_len)
            {
                int acc = 0;
                for (int i = 0; i < wire_len; i++)
                    acc += p[i];
                return acc;
            }
            int main(void)
            {
                uint8_t *buf = malloc(16);
                if (!buf)
                    return 2;
                memset(buf, 1, 16);
                int v = decode(buf, 17);   /* one past the heap block */
                free(buf);
                return v == 0 ? 0 : 1;
            }
            """))
        exe = tmp_path / "overflow"
        comp = subprocess.run(
            [_cc()] + _SANITIZE_FLAGS + [str(src), "-o", str(exe)],
            capture_output=True, text=True, timeout=120)
        if comp.returncode != 0:
            pytest.skip(f"sanitizer compile unavailable: {comp.stderr[:200]}")
        run = subprocess.run(
            [str(exe)], capture_output=True, text=True, timeout=60,
            env=dict(os.environ,
                     ASAN_OPTIONS="detect_leaks=0:halt_on_error=1"))
        assert run.returncode != 0
        assert "AddressSanitizer" in run.stderr
        assert "heap-buffer-overflow" in run.stderr

    def test_sanitized_build_cache_is_separate(self):
        """The ASan .so cache key (build/asan/<mod>.so) never collides
        with the regular in-place build (<pkg>/<mod>.<tag>.so)."""
        from stellar_core_tpu import _native_build as nb
        assert os.path.basename(nb._ASAN_DIR) == "asan"
        assert not nb._ASAN_DIR.startswith(nb._PKG)
