"""CAP-33 sponsored-reserve tests.

Mirrors reference coverage in src/transactions/test/
{BeginSponsoringFutureReservesTests, EndSponsoringFutureReservesTests,
RevokeSponsorshipTests}.cpp: sandwiched entry/signer creation for every
sponsorable type, revoke transfer/remove on both arms, reserve-failure
paths, and the tx-level txBAD_SPONSORSHIP for unclosed sandwiches —
driven through LedgerManager.close_ledger with all invariants enabled
(SponsorshipCountIsValid validates every close's bookkeeping).
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.testutils import (TestAccount, build_tx,
                                        change_trust_op, create_account_op,
                                        make_asset, manage_sell_offer_op,
                                        native_payment_op, network_id)
from stellar_core_tpu.transactions import sponsorship
from stellar_core_tpu.transactions.utils import (num_sponsored,
                                                 num_sponsoring)

NID = network_id("tpu-core sponsorship network")


@pytest.fixture
def mgr():
    m = LedgerManager(NID)
    m.start_new_ledger()
    return m


@pytest.fixture
def root(mgr):
    sk = mgr.root_account_secret()
    acc = mgr.root.get_entry(
        X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    return TestAccount(mgr, sk, acc.data.value.seqNum)


def _close(mgr, *frames, close_time=1000):
    return mgr.close_ledger(list(frames), close_time)


def _result_of(arts, frame):
    for pair in arts.result_entry.txResultSet.results:
        if pair.transactionHash == frame.content_hash():
            return pair.result
    raise AssertionError("tx not in result set")


def _acc_entry(mgr, account_id):
    return mgr.root.get_entry(X.LedgerKey.account(
        X.LedgerKeyAccount(accountID=account_id)).to_xdr())


def _acc(mgr, account_id):
    e = _acc_entry(mgr, account_id)
    return e.data.value if e else None


def _mk(mgr, root, seed, balance=20_000_000_000):
    sk = SecretKey(bytes([seed]) * 32)
    _close(mgr, root.tx([create_account_op(
        X.AccountID.ed25519(sk.public_key.ed25519), balance)]))
    acc = _acc(mgr, X.AccountID.ed25519(sk.public_key.ed25519))
    return TestAccount(mgr, sk, acc.seqNum)


def begin_op(sponsored: X.AccountID, source=None):
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.beginSponsoringFutureReservesOp(
            X.BeginSponsoringFutureReservesOp(sponsoredID=sponsored)))


def end_op(source=None):
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.endSponsoringFutureReserves())


def revoke_entry_op(key: X.LedgerKey, source=None):
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.revokeSponsorshipOp(
            X.RevokeSponsorshipOp.ledgerKey(key)))


def revoke_signer_op(account: X.AccountID, signer_key: X.SignerKey,
                     source=None):
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.revokeSponsorshipOp(
            X.RevokeSponsorshipOp.signer(X.RevokeSponsorshipOpSigner(
                accountID=account, signerKey=signer_key))))


def set_signer_op(key_bytes: bytes, weight: int, source=None):
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.setOptionsOp(X.SetOptionsOp(
            signer=X.Signer(key=X.SignerKey.ed25519(key_bytes),
                            weight=weight))))


def manage_data_op(name: bytes, value, source=None):
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.manageDataOp(X.ManageDataOp(
            dataName=name, dataValue=value)))


def _sandwich_tx(sponsor: TestAccount, sponsored: TestAccount, ops):
    """sponsor Begins for `sponsored`, the sandwiched ops run as
    `sponsored`'s, then `sponsored` Ends — all one tx signed by both."""
    body = [begin_op(sponsored.account_id, source=sponsor.account_id)]
    body += ops
    body.append(end_op(source=sponsored.account_id))
    return build_tx(NID, sponsor.secret, sponsor.next_seq(), body,
                    extra_signers=[sponsored.secret])


# --- sponsored creation, one per entry type --------------------------------

def test_sponsored_create_account_zero_balance(mgr, root):
    s = _mk(mgr, root, 1)
    new_sk = SecretKey(bytes([9]) * 32)
    new_id = X.AccountID.ed25519(new_sk.public_key.ed25519)
    # destination sandwiched: the sponsor covers the 2 base reserves, so a
    # 0-balance create succeeds at v14+
    ops = [begin_op(new_id, source=s.account_id),
           create_account_op(new_id, 0, source=s.account_id),
           end_op(source=new_id)]
    tx = build_tx(NID, s.secret, s.next_seq(), ops,
                  extra_signers=[new_sk])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txSUCCESS
    new_e = _acc_entry(mgr, new_id)
    assert new_e.ext.switch == 1
    assert new_e.ext.value.sponsoringID == s.account_id
    assert num_sponsoring(_acc(mgr, s.account_id)) == 2
    assert num_sponsored(_acc(mgr, new_id)) == 2


def test_unsponsored_zero_balance_create_fails(mgr, root):
    s = _mk(mgr, root, 2)
    new_id = X.AccountID.ed25519(SecretKey(bytes([8]) * 32).public_key.ed25519)
    tx = s.tx([create_account_op(new_id, 0)])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    op_res = res.result.value[0].value.value
    assert op_res.switch == X.CreateAccountResultCode.CREATE_ACCOUNT_LOW_RESERVE


def test_sponsored_trustline(mgr, root):
    s = _mk(mgr, root, 3)
    a = _mk(mgr, root, 4)
    issuer = _mk(mgr, root, 5)
    asset = make_asset("USD", issuer.account_id)
    tx = _sandwich_tx(s, a, [change_trust_op(asset, source=a.account_id)])
    arts = _close(mgr, tx)
    assert _result_of(arts, tx).result.switch == \
        X.TransactionResultCode.txSUCCESS
    tl = mgr.root.get_entry(X.LedgerKey.trustLine(X.LedgerKeyTrustLine(
        accountID=a.account_id,
        asset=X.TrustLineAsset(asset.switch, asset.value))).to_xdr())
    assert tl.ext.switch == 1 and tl.ext.value.sponsoringID == s.account_id
    assert num_sponsoring(_acc(mgr, s.account_id)) == 1
    assert num_sponsored(_acc(mgr, a.account_id)) == 1
    acc_a = _acc(mgr, a.account_id)
    assert acc_a.numSubEntries == 1


def test_sponsored_data_entry_and_offer(mgr, root):
    s = _mk(mgr, root, 6)
    a = _mk(mgr, root, 7)
    issuer = _mk(mgr, root, 8)
    asset = make_asset("EUR", issuer.account_id)
    # data entry
    tx = _sandwich_tx(s, a, [manage_data_op(b"k1", b"v1",
                                            source=a.account_id)])
    arts = _close(mgr, tx)
    assert _result_of(arts, tx).result.switch == \
        X.TransactionResultCode.txSUCCESS
    # offer needs a trustline first (unsponsored, a pays)
    _close(mgr, a.tx([change_trust_op(asset)]))
    tx2 = _sandwich_tx(s, a, [manage_sell_offer_op(
        X.Asset.native(), asset, 1000, 1, 1, source=a.account_id)])
    arts2 = _close(mgr, tx2)
    assert _result_of(arts2, tx2).result.switch == \
        X.TransactionResultCode.txSUCCESS
    assert num_sponsoring(_acc(mgr, s.account_id)) == 2  # data + offer
    assert num_sponsored(_acc(mgr, a.account_id)) == 2


def test_sponsored_signer(mgr, root):
    s = _mk(mgr, root, 10)
    a = _mk(mgr, root, 11)
    signer_pk = SecretKey(bytes([12]) * 32).public_key.ed25519
    tx = _sandwich_tx(s, a, [set_signer_op(signer_pk, 1,
                                           source=a.account_id)])
    arts = _close(mgr, tx)
    assert _result_of(arts, tx).result.switch == \
        X.TransactionResultCode.txSUCCESS
    acc = _acc(mgr, a.account_id)
    assert num_sponsored(acc) == 1
    assert num_sponsoring(_acc(mgr, s.account_id)) == 1
    ids = sponsorship.signer_sponsoring_ids(acc)
    assert len(ids) == len(acc.signers) == 1
    assert ids[0] == s.account_id
    # removing the sponsored signer releases the sponsor
    arts2 = _close(mgr, a.tx([set_signer_op(signer_pk, 0)]))
    assert num_sponsoring(_acc(mgr, s.account_id)) == 0
    assert num_sponsored(_acc(mgr, a.account_id)) == 0
    assert len(_acc(mgr, a.account_id).signers) == 0


def test_signer_sponsoring_ids_stay_aligned(mgr, root):
    """Unsponsored + sponsored signers interleaved: the ids array tracks
    the sorted signer list index-for-index."""
    s = _mk(mgr, root, 13)
    a = _mk(mgr, root, 14)
    pks = sorted(bytes([x]) * 32 for x in (40, 140, 240))
    # add middle signer unsponsored, then outer two sponsored
    _close(mgr, a.tx([set_signer_op(pks[1], 1)]))
    tx = _sandwich_tx(s, a, [set_signer_op(pks[0], 1, source=a.account_id),
                             set_signer_op(pks[2], 1, source=a.account_id)])
    arts = _close(mgr, tx)
    assert _result_of(arts, tx).result.switch == \
        X.TransactionResultCode.txSUCCESS
    acc = _acc(mgr, a.account_id)
    keys = [s_.key.value for s_ in acc.signers]
    assert keys == pks  # sorted by key xdr (same tag => by bytes)
    ids = sponsorship.signer_sponsoring_ids(acc)
    assert ids[0] == s.account_id
    assert ids[1] is None
    assert ids[2] == s.account_id


# --- failure paths ---------------------------------------------------------

def test_unclosed_sandwich_fails_tx(mgr, root):
    s = _mk(mgr, root, 15)
    a = _mk(mgr, root, 16)
    tx = build_tx(NID, s.secret, s.next_seq(),
                  [begin_op(a.account_id)])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txBAD_SPONSORSHIP
    # nothing leaked into the ledger
    assert num_sponsoring(_acc(mgr, s.account_id)) == 0


def test_end_without_begin(mgr, root):
    a = _mk(mgr, root, 17)
    tx = a.tx([end_op()])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    op_res = res.result.value[0].value.value
    assert op_res.switch == X.EndSponsoringFutureReservesResultCode.\
        END_SPONSORING_FUTURE_RESERVES_NOT_SPONSORED


def test_sponsor_low_reserve(mgr, root):
    # sponsor with exactly its own min balance cannot take a sponsorship
    base_reserve = mgr.lcl_header.baseReserve
    s = _mk(mgr, root, 18, balance=2 * base_reserve + 100)
    a = _mk(mgr, root, 19)
    signer_pk = SecretKey(bytes([20]) * 32).public_key.ed25519
    tx = _sandwich_tx(s, a, [set_signer_op(signer_pk, 1,
                                           source=a.account_id)])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    codes = [r.value.value.switch for r in res.result.value
             if r.switch == X.OperationResultCode.opINNER]
    assert X.SetOptionsResultCode.SET_OPTIONS_LOW_RESERVE in codes


def test_begin_recursive_and_already(mgr, root):
    s = _mk(mgr, root, 21)
    a = _mk(mgr, root, 22)
    b = _mk(mgr, root, 23)
    # already sponsored: two Begins for the same account
    tx = build_tx(NID, s.secret, s.next_seq(),
                  [begin_op(a.account_id),
                   begin_op(a.account_id, source=b.account_id),
                   end_op(source=a.account_id)],
                  extra_signers=[a.secret, b.secret])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    op1 = res.result.value[1].value.value
    assert op1.switch == X.BeginSponsoringFutureReservesResultCode.\
        BEGIN_SPONSORING_FUTURE_RESERVES_ALREADY_SPONSORED
    # recursive: a (sponsored) begins for someone else
    tx2 = build_tx(NID, s.secret, s.next_seq(),
                   [begin_op(a.account_id),
                    begin_op(b.account_id, source=a.account_id),
                    end_op(source=a.account_id)],
                   extra_signers=[a.secret, b.secret])
    arts2 = _close(mgr, tx2)
    res2 = _result_of(arts2, tx2)
    assert res2.result.switch == X.TransactionResultCode.txFAILED
    op21 = res2.result.value[1].value.value
    assert op21.switch == X.BeginSponsoringFutureReservesResultCode.\
        BEGIN_SPONSORING_FUTURE_RESERVES_RECURSIVE


# --- revoke: ledger-entry arm ---------------------------------------------

def _sponsored_trustline(mgr, root, s, a, issuer_seed=50, code="GBP"):
    issuer = _mk(mgr, root, issuer_seed)
    asset = make_asset(code, issuer.account_id)
    tx = _sandwich_tx(s, a, [change_trust_op(asset, source=a.account_id)])
    arts = _close(mgr, tx)
    assert _result_of(arts, tx).result.switch == \
        X.TransactionResultCode.txSUCCESS
    key = X.LedgerKey.trustLine(X.LedgerKeyTrustLine(
        accountID=a.account_id,
        asset=X.TrustLineAsset(asset.switch, asset.value)))
    return key


def test_revoke_remove_returns_reserve_to_owner(mgr, root):
    s = _mk(mgr, root, 24)
    a = _mk(mgr, root, 25)
    key = _sponsored_trustline(mgr, root, s, a, 26)
    # the current sponsor revokes with no sandwich: reserve moves to owner
    tx = s.tx([revoke_entry_op(key)])
    arts = _close(mgr, tx)
    assert _result_of(arts, tx).result.switch == \
        X.TransactionResultCode.txSUCCESS
    tl = mgr.root.get_entry(key.to_xdr())
    assert tl.ext.switch == 0
    assert num_sponsoring(_acc(mgr, s.account_id)) == 0
    assert num_sponsored(_acc(mgr, a.account_id)) == 0


def test_revoke_transfer_while_sandwiched(mgr, root):
    s1 = _mk(mgr, root, 27)
    s2 = _mk(mgr, root, 28)
    a = _mk(mgr, root, 29)
    key = _sponsored_trustline(mgr, root, s1, a, 30)
    # canonical transfer: s2 begins FOR s1 (current sponsor), s1 revokes
    tx = _sandwich_tx(s2, s1, [revoke_entry_op(key, source=s1.account_id)])
    arts = _close(mgr, tx)
    assert _result_of(arts, tx).result.switch == \
        X.TransactionResultCode.txSUCCESS
    tl = mgr.root.get_entry(key.to_xdr())
    assert tl.ext.value.sponsoringID == s2.account_id
    assert num_sponsoring(_acc(mgr, s1.account_id)) == 0
    assert num_sponsoring(_acc(mgr, s2.account_id)) == 1
    assert num_sponsored(_acc(mgr, a.account_id)) == 1  # unchanged


def test_revoke_establish_on_unsponsored_entry(mgr, root):
    s = _mk(mgr, root, 31)
    a = _mk(mgr, root, 32)
    issuer = _mk(mgr, root, 33)
    asset = make_asset("JPY", issuer.account_id)
    _close(mgr, a.tx([change_trust_op(asset)]))   # unsponsored
    key = X.LedgerKey.trustLine(X.LedgerKeyTrustLine(
        accountID=a.account_id,
        asset=X.TrustLineAsset(asset.switch, asset.value)))
    # owner inside a sandwich revokes -> establishes sponsorship to s
    tx = _sandwich_tx(s, a, [revoke_entry_op(key, source=a.account_id)])
    arts = _close(mgr, tx)
    assert _result_of(arts, tx).result.switch == \
        X.TransactionResultCode.txSUCCESS
    tl = mgr.root.get_entry(key.to_xdr())
    assert tl.ext.value.sponsoringID == s.account_id
    assert num_sponsoring(_acc(mgr, s.account_id)) == 1
    assert num_sponsored(_acc(mgr, a.account_id)) == 1


def test_revoke_not_sponsor(mgr, root):
    s = _mk(mgr, root, 34)
    a = _mk(mgr, root, 35)
    b = _mk(mgr, root, 36)
    key = _sponsored_trustline(mgr, root, s, a, 37)
    for actor in (a, b):   # neither the owner nor a stranger may revoke
        tx = actor.tx([revoke_entry_op(key)])
        arts = _close(mgr, tx)
        res = _result_of(arts, tx)
        assert res.result.switch == X.TransactionResultCode.txFAILED
        op_res = res.result.value[0].value.value
        assert op_res.switch == X.RevokeSponsorshipResultCode.\
            REVOKE_SPONSORSHIP_NOT_SPONSOR


def test_revoke_remove_low_reserve_on_owner(mgr, root):
    base_reserve = mgr.lcl_header.baseReserve
    s = _mk(mgr, root, 38)
    # owner kept at the bare minimum for (2 + 1 subentry - 1 sponsored)
    a = _mk(mgr, root, 39, balance=2 * base_reserve + 200)
    key = _sponsored_trustline(mgr, root, s, a, 40)
    tx = s.tx([revoke_entry_op(key)])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    op_res = res.result.value[0].value.value
    assert op_res.switch == X.RevokeSponsorshipResultCode.\
        REVOKE_SPONSORSHIP_LOW_RESERVE


def test_revoke_claimable_balance_only_transferable(mgr, root):
    s = _mk(mgr, root, 41)
    a = _mk(mgr, root, 42)
    cb = X.Operation(body=X.OperationBody.createClaimableBalanceOp(
        X.CreateClaimableBalanceOp(
            asset=X.Asset.native(), amount=5_000_000,
            claimants=[X.Claimant.v0(X.ClaimantV0(
                destination=a.account_id,
                predicate=X.ClaimPredicate.unconditional()))])))
    tx = s.tx([cb])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txSUCCESS
    bid = res.result.value[0].value.value.value
    key = X.LedgerKey.claimableBalance(
        X.LedgerKeyClaimableBalance(balanceID=bid))
    tx2 = s.tx([revoke_entry_op(key)])
    arts2 = _close(mgr, tx2)
    res2 = _result_of(arts2, tx2)
    assert res2.result.switch == X.TransactionResultCode.txFAILED
    op_res = res2.result.value[0].value.value
    assert op_res.switch == X.RevokeSponsorshipResultCode.\
        REVOKE_SPONSORSHIP_ONLY_TRANSFERABLE


def test_revoke_claimable_balance_transfer(mgr, root):
    s1 = _mk(mgr, root, 43)
    s2 = _mk(mgr, root, 44)
    a = _mk(mgr, root, 45)
    cb = X.Operation(body=X.OperationBody.createClaimableBalanceOp(
        X.CreateClaimableBalanceOp(
            asset=X.Asset.native(), amount=5_000_000,
            claimants=[X.Claimant.v0(X.ClaimantV0(
                destination=a.account_id,
                predicate=X.ClaimPredicate.unconditional()))])))
    tx = s1.tx([cb])
    arts = _close(mgr, tx)
    bid = _result_of(arts, tx).result.value[0].value.value.value
    key = X.LedgerKey.claimableBalance(
        X.LedgerKeyClaimableBalance(balanceID=bid))
    tx2 = _sandwich_tx(s2, s1, [revoke_entry_op(key, source=s1.account_id)])
    arts2 = _close(mgr, tx2)
    assert _result_of(arts2, tx2).result.switch == \
        X.TransactionResultCode.txSUCCESS
    cb_e = mgr.root.get_entry(key.to_xdr())
    assert cb_e.ext.value.sponsoringID == s2.account_id
    assert num_sponsoring(_acc(mgr, s1.account_id)) == 0
    assert num_sponsoring(_acc(mgr, s2.account_id)) == 1


# --- revoke: signer arm ----------------------------------------------------

def test_revoke_signer_remove_and_transfer(mgr, root):
    s1 = _mk(mgr, root, 46)
    s2 = _mk(mgr, root, 47)
    a = _mk(mgr, root, 48)
    signer_pk = SecretKey(bytes([49]) * 32).public_key.ed25519
    skey = X.SignerKey.ed25519(signer_pk)
    tx = _sandwich_tx(s1, a, [set_signer_op(signer_pk, 1,
                                            source=a.account_id)])
    _close(mgr, tx)
    assert num_sponsoring(_acc(mgr, s1.account_id)) == 1
    # transfer s1 -> s2
    tx2 = _sandwich_tx(s2, s1, [revoke_signer_op(a.account_id, skey,
                                                 source=s1.account_id)])
    arts2 = _close(mgr, tx2)
    assert _result_of(arts2, tx2).result.switch == \
        X.TransactionResultCode.txSUCCESS
    acc = _acc(mgr, a.account_id)
    assert sponsorship.signer_sponsoring_ids(acc)[0] == s2.account_id
    assert num_sponsoring(_acc(mgr, s1.account_id)) == 0
    assert num_sponsoring(_acc(mgr, s2.account_id)) == 1
    # remove: s2 revokes outside any sandwich
    tx3 = s2.tx([revoke_signer_op(a.account_id, skey)])
    arts3 = _close(mgr, tx3)
    assert _result_of(arts3, tx3).result.switch == \
        X.TransactionResultCode.txSUCCESS
    acc = _acc(mgr, a.account_id)
    assert sponsorship.signer_sponsoring_ids(acc)[0] is None
    assert num_sponsoring(_acc(mgr, s2.account_id)) == 0
    assert num_sponsored(acc) == 0
    assert len(acc.signers) == 1   # the signer itself stays


def test_revoke_signer_missing(mgr, root):
    a = _mk(mgr, root, 51)
    skey = X.SignerKey.ed25519(bytes([52]) * 32)
    tx = a.tx([revoke_signer_op(a.account_id, skey)])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    op_res = res.result.value[0].value.value
    assert op_res.switch == X.RevokeSponsorshipResultCode.\
        REVOKE_SPONSORSHIP_DOES_NOT_EXIST


# --- lifecycle: sponsored entries released on deletion ---------------------

def test_sponsored_trustline_delete_releases_sponsor(mgr, root):
    s = _mk(mgr, root, 53)
    a = _mk(mgr, root, 54)
    issuer = _mk(mgr, root, 55)
    asset = make_asset("CAD", issuer.account_id)
    tx = _sandwich_tx(s, a, [change_trust_op(asset, source=a.account_id)])
    _close(mgr, tx)
    assert num_sponsoring(_acc(mgr, s.account_id)) == 1
    _close(mgr, a.tx([change_trust_op(asset, limit=0)]))
    assert num_sponsoring(_acc(mgr, s.account_id)) == 0
    assert num_sponsored(_acc(mgr, a.account_id)) == 0


def test_sponsored_account_merge_releases_sponsor(mgr, root):
    s = _mk(mgr, root, 56)
    payer = _mk(mgr, root, 57)
    new_sk = SecretKey(bytes([58]) * 32)
    new_id = X.AccountID.ed25519(new_sk.public_key.ed25519)
    ops = [begin_op(new_id, source=s.account_id),
           create_account_op(new_id, 1_000_000_000, source=payer.account_id),
           end_op(source=new_id)]
    tx = build_tx(NID, s.secret, s.next_seq(), ops,
                  extra_signers=[payer.secret, new_sk])
    _close(mgr, tx)
    assert num_sponsoring(_acc(mgr, s.account_id)) == 2
    new_acc = _acc(mgr, new_id)
    merge = build_tx(NID, new_sk, new_acc.seqNum + 1, [X.Operation(
        body=X.OperationBody.destination(
            X.muxed_from_account_id(payer.account_id)))])
    arts = _close(mgr, merge)
    assert _result_of(arts, merge).result.switch == \
        X.TransactionResultCode.txSUCCESS
    assert _acc(mgr, new_id) is None
    assert num_sponsoring(_acc(mgr, s.account_id)) == 0


# --- AccountMerge inside an open sandwich (ADVICE r5 high) -----------------

def _merge_op(dest: X.AccountID, source=None):
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.destination(X.muxed_from_account_id(dest)))


def test_merge_rejected_for_sandwich_sponsor(mgr, root):
    """[Begin(S sponsors A), AccountMerge(source=S), End(A)] must fail
    ACCOUNT_MERGE_IS_SPONSOR (reference: MergeOpFrame via
    loadSponsorshipCounter) — previously it merged S away mid-sandwich."""
    s = _mk(mgr, root, 70)
    a = _mk(mgr, root, 71)
    ops = [begin_op(a.account_id, source=s.account_id),
           _merge_op(root.account_id, source=s.account_id),
           end_op(source=a.account_id)]
    tx = build_tx(NID, s.secret, s.next_seq(), ops,
                  extra_signers=[a.secret])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    op_res = res.result.value[1].value.value
    assert op_res.switch == \
        X.AccountMergeResultCode.ACCOUNT_MERGE_IS_SPONSOR
    assert _acc(mgr, s.account_id) is not None   # sponsor survived


def test_merge_rejected_for_sandwiched_account(mgr, root):
    """The SPONSORED party of an open sandwich cannot merge either
    (reference: loadSponsorship arm of the same check)."""
    s = _mk(mgr, root, 72)
    a = _mk(mgr, root, 73)
    ops = [begin_op(a.account_id, source=s.account_id),
           _merge_op(root.account_id, source=a.account_id),
           end_op(source=a.account_id)]
    tx = build_tx(NID, s.secret, s.next_seq(), ops,
                  extra_signers=[a.secret])
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    op_res = res.result.value[1].value.value
    assert op_res.switch == \
        X.AccountMergeResultCode.ACCOUNT_MERGE_IS_SPONSOR
    assert _acc(mgr, a.account_id) is not None


def test_merge_outside_sandwich_still_succeeds(mgr, root):
    """A closed sandwich leaves no trace: the same accounts merge fine in
    a later tx."""
    s = _mk(mgr, root, 74)
    a = _mk(mgr, root, 75)
    tx = _sandwich_tx(s, a, [manage_data_op(b"k", b"v",
                                            source=a.account_id)])
    _close(mgr, tx)
    # undo the sponsored subentry so the merge precondition holds
    _close(mgr, a.tx([manage_data_op(b"k", None)]))
    merge = s.tx([_merge_op(root.account_id)])
    arts = _close(mgr, merge)
    assert _result_of(arts, merge).result.switch == \
        X.TransactionResultCode.txSUCCESS
    assert _acc(mgr, s.account_id) is None


# --- mutate-then-fail isolation (ADVICE r5 medium) -------------------------

def test_failed_op_leaves_no_counter_mutations(mgr, root):
    """A sponsored CreateAccount that fails UNDERFUNDED (after having
    established the sponsorship) must roll back its counter mutations, so
    a LATER op of the same (failing) tx sees clean state — the per-op
    nested LedgerTxn, reference: applyOperations' ltxOp.

    S is funded to afford sponsoring exactly ONE more account (4 base
    reserves = 4e8): with the old shared-ltx behavior the failed op's
    leaked numSponsoring += 2 made op 4 fail LOW_RESERVE (needs 6e8);
    rolled back properly, op 4 SUCCEEDS inside the failed tx — the op
    result vector (and thus txSetResultHash on replay) differs."""
    fee = 4 * 100
    s = _mk(mgr, root, 76, balance=500_000_000 + fee)
    a1 = X.AccountID.ed25519(SecretKey(bytes([77]) * 32).public_key.ed25519)
    a2 = X.AccountID.ed25519(SecretKey(bytes([78]) * 32).public_key.ed25519)
    ops = [begin_op(a1, source=s.account_id),
           create_account_op(a1, 10 ** 18, source=s.account_id),  # UNDERFUNDED
           begin_op(a2, source=s.account_id),
           create_account_op(a2, 0, source=s.account_id)]
    tx = build_tx(NID, s.secret, s.next_seq(), ops)
    arts = _close(mgr, tx)
    res = _result_of(arts, tx)
    assert res.result.switch == X.TransactionResultCode.txFAILED
    op1 = res.result.value[1].value.value
    assert op1.switch == \
        X.CreateAccountResultCode.CREATE_ACCOUNT_UNDERFUNDED
    op3 = res.result.value[3].value.value
    assert op3.switch == X.CreateAccountResultCode.CREATE_ACCOUNT_SUCCESS
    # the tx failed as a whole: nothing persisted
    assert _acc(mgr, a1) is None and _acc(mgr, a2) is None
    assert num_sponsoring(_acc(mgr, s.account_id)) == 0
