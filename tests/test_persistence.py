"""Durable persistence + restart tests.

Reference test model: src/ledger/test/LedgerCloseMetaStreamTests /
LedgerManagerTests (loadLastKnownLedger), src/database/test/ and
src/history/test (publish queue persistence): a node killed at any point
must restart from its DB + bucket files and continue producing the same
hash chain.
"""

import os

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.bucket.manager import BucketDir
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.database import Database, PersistentState
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.testutils import (TestAccount, change_trust_op,
                                        create_account_op, make_asset,
                                        manage_sell_offer_op, network_id,
                                        payment_op)

NID = network_id("persistence test net")


def _root_of(mgr):
    sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    return TestAccount(mgr, sk, e.data.value.seqNum)


def _run_some_ledgers(mgr, root, n_extra=3):
    issuer_sk = SecretKey(b"\x21" * 32)
    issuer_id = X.AccountID.ed25519(issuer_sk.public_key.ed25519)
    mgr.close_ledger([root.tx([create_account_op(issuer_id, 10**12)])], 1000)
    e = mgr.root.get_entry(X.LedgerKey.account(
        X.LedgerKeyAccount(accountID=issuer_id)).to_xdr())
    issuer = TestAccount(mgr, issuer_sk, e.data.value.seqNum)
    eur = make_asset("EUR", issuer_id)
    native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None)
    mgr.close_ledger([root.tx([change_trust_op(eur)])], 1001)
    mgr.close_ledger([issuer.tx([payment_op(root.account_id, eur, 5000)])],
                     1002)
    mgr.close_ledger([root.tx([manage_sell_offer_op(eur, native, 100, 2, 1)])],
                     1003)
    for i in range(n_extra):
        mgr.close_ledger([issuer.tx([payment_op(root.account_id, eur, 10)])],
                         1004 + i)
    return issuer


def test_restart_resumes_exact_state_and_hash_chain(tmp_path):
    db_path = str(tmp_path / "node.db")
    bdir = BucketDir(str(tmp_path / "buckets"))
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    mgr.enable_persistence(Database(db_path), bdir)
    root = _root_of(mgr)
    issuer = _run_some_ledgers(mgr, root)
    lcl_hash, lcl_seq = mgr.lcl_hash, mgr.last_closed_ledger_seq
    n_entries = mgr.root.entry_count()
    mgr.db.close()
    del mgr  # "kill -9": nothing but disk survives

    db = Database(db_path)
    mgr2 = LedgerManager.load_last_known_ledger(NID, db, bdir)
    assert mgr2.lcl_hash == lcl_hash
    assert mgr2.last_closed_ledger_seq == lcl_seq
    assert mgr2.root.entry_count() == n_entries

    # the resumed node and an uninterrupted twin must produce identical
    # hashes for the same subsequent traffic
    twin = LedgerManager(NID)
    twin.start_new_ledger()
    twin_root = _root_of(twin)
    _run_some_ledgers(twin, twin_root)
    assert twin.lcl_hash == mgr2.lcl_hash

    for m, r in ((mgr2, _root_of(mgr2)), (twin, _root_of(twin))):
        dest = SecretKey(b"\x22" * 32)
        m.close_ledger([r.tx([create_account_op(
            X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])], 2000)
    assert mgr2.lcl_hash == twin.lcl_hash
    assert mgr2.last_closed_ledger_seq == lcl_seq + 1


def test_restart_mid_stream_headers_queryable(tmp_path):
    db = Database(str(tmp_path / "node.db"))
    bdir = BucketDir(str(tmp_path / "buckets"))
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    mgr.enable_persistence(db, bdir)
    root = _root_of(mgr)
    _run_some_ledgers(mgr, root, n_extra=0)
    got = db.load_header_by_seq(3)
    assert got is not None
    h, header = got
    assert header.ledgerSeq == 3
    from stellar_core_tpu.crypto.sha import sha256
    assert sha256(header.to_xdr()) == h
    assert db.max_header_seq() == mgr.last_closed_ledger_seq


def test_load_refuses_wrong_network(tmp_path):
    db = Database(str(tmp_path / "node.db"))
    bdir = BucketDir(str(tmp_path / "buckets"))
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    mgr.enable_persistence(db, bdir)
    with pytest.raises(RuntimeError, match="different network"):
        LedgerManager.load_last_known_ledger(
            network_id("some other net"), db, bdir)


def test_load_detects_corrupt_bucket_file(tmp_path):
    db = Database(str(tmp_path / "node.db"))
    bdir = BucketDir(str(tmp_path / "buckets"))
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    mgr.enable_persistence(db, bdir)
    root = _root_of(mgr)
    _run_some_ledgers(mgr, root, n_extra=0)
    victims = [n for n in os.listdir(bdir.path) if n.endswith(".xdr")]
    assert victims
    path = os.path.join(bdir.path, victims[0])
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    with pytest.raises(RuntimeError, match="hash check|missing bucket"):
        LedgerManager.load_last_known_ledger(NID, db, bdir)


def test_manifest_torn_line_does_not_brick_startup(tmp_path):
    """A crash mid manifest append leaves a malformed tail line; the
    startup audit must treat it as absent (the full-file hash scan still
    covers every real file), not fail-stop on garbage forever."""
    db = Database(str(tmp_path / "node.db"))
    bdir = BucketDir(str(tmp_path / "buckets"))
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    mgr.enable_persistence(db, bdir)
    root = _root_of(mgr)
    _run_some_ledgers(mgr, root, n_extra=0)
    with open(bdir._manifest_path, "a") as f:
        f.write("deadbeef\n")            # truncated entry
        f.write("bucket-trailing-junk")  # no newline, wrong shape
    mgr2 = LedgerManager.load_last_known_ledger(NID, db, bdir)
    assert mgr2.lcl_hash == mgr.lcl_hash


def test_manifest_append_after_torn_tail_stays_tracked(tmp_path):
    """An append landing after a crash-torn tail line must not glue onto
    the fragment (invalidating both): the new entry has to survive a
    fresh read so the bucket stays audit-tracked."""
    bdir = BucketDir(str(tmp_path / "buckets"))
    with open(bdir._manifest_path, "w") as f:
        f.write("a" * 64 + "\n")
        f.write("bb")  # torn tail, no newline
    bdir._manifest_cache = None  # cold read, like a restart
    hh = "c" * 64
    bdir._manifest_add(hh)
    fresh = BucketDir(str(tmp_path / "buckets"))
    assert hh in fresh._manifest_read()
    assert "a" * 64 in fresh._manifest_read()


def test_load_detects_missing_bucket(tmp_path):
    db = Database(str(tmp_path / "node.db"))
    bdir = BucketDir(str(tmp_path / "buckets"))
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    mgr.enable_persistence(db, bdir)
    root = _root_of(mgr)
    _run_some_ledgers(mgr, root, n_extra=0)
    for n in os.listdir(bdir.path):
        if n.endswith(".xdr"):
            os.unlink(os.path.join(bdir.path, n))
            break
    with pytest.raises(RuntimeError, match="missing bucket"):
        LedgerManager.load_last_known_ledger(NID, db, bdir)


def test_bucket_dir_gc_keeps_referenced(tmp_path):
    db = Database(str(tmp_path / "node.db"))
    bdir = BucketDir(str(tmp_path / "buckets"))
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    mgr.enable_persistence(db, bdir)
    root = _root_of(mgr)
    _run_some_ledgers(mgr, root)
    removed = bdir.gc(mgr.bucket_list.referenced_hashes())
    assert removed > 0  # superseded level-0 currs from earlier closes
    # everything needed for restart still present
    mgr.db.close()
    mgr2 = LedgerManager.load_last_known_ledger(NID, Database(db.path), bdir)
    assert mgr2.lcl_hash == mgr.lcl_hash


def test_scp_history_and_publish_queue_roundtrip(tmp_path):
    db = Database(str(tmp_path / "node.db"))
    qset = X.SCPQuorumSet(threshold=1, validators=[], innerSets=[])
    env = X.SCPEnvelope(
        statement=X.SCPStatement(
            nodeID=X.AccountID.ed25519(b"\x01" * 32), slotIndex=7,
            pledges=X.SCPStatementPledges.nominate(X.SCPNomination(
                quorumSetHash=b"\x02" * 32, votes=[], accepted=[]))),
        signature=b"\x03" * 64)
    db.save_scp_history(7, [env], [qset])
    db.queue_publish(63, '{"fake": "has"}')
    db.commit()
    db.close()

    db2 = Database(db.path)
    envs = db2.load_scp_history(7)
    assert len(envs) == 1 and envs[0].to_xdr() == env.to_xdr()
    assert [q.to_xdr() for q in db2.load_scp_quorums()] == [qset.to_xdr()]
    assert db2.publish_queue() == [(63, '{"fake": "has"}')]
    db2.dequeue_publish(63)
    assert db2.publish_queue() == []


def test_persistent_state_kv(tmp_path):
    db = Database(str(tmp_path / "node.db"))
    assert db.get_state("nope") is None
    db.set_state(PersistentState.NETWORK_PASSPHRASE, "abc")
    db.set_state(PersistentState.NETWORK_PASSPHRASE, "def")
    db.commit()
    assert db.get_state(PersistentState.NETWORK_PASSPHRASE) == "def"


def test_node_restart_rejoins_and_continues_consensus(tmp_path):
    """kill -9 a running single-validator node; restart from DB + bucket
    files; it resumes from its LCL, restores SCP state, and keeps closing
    ledgers on the same hash chain (reference: loadLastKnownLedger +
    HerderImpl::restoreSCPState on startup)."""
    from stellar_core_tpu.simulation import Simulation, qset_of

    sk = SecretKey(b"\x31" * 32)
    q = qset_of([sk.public_key.ed25519], 1)
    db_path = str(tmp_path / "node.db")
    bdir = BucketDir(str(tmp_path / "buckets"))

    sim = Simulation(b"restart net")
    node = sim.add_node(sk, q)
    node.lm.enable_persistence(Database(db_path), bdir)
    node.herder.attach_persistence(node.lm.db)
    sim.start_all_nodes()
    assert sim.crank_until_ledger(4, timeout=120)
    lcl_seq, lcl_hash = node.lcl, node.lcl_hash
    node.lm.db.close()
    del node, sim  # kill -9

    sim2 = Simulation(b"restart net")
    db = Database(db_path)
    lm = LedgerManager.load_last_known_ledger(sim2.network_id, db, bdir)
    assert lm.last_closed_ledger_seq >= lcl_seq
    node2 = sim2.add_node(sk, q, ledger_manager=lm)
    node2.herder.attach_persistence(db)
    node2.herder.restore_scp_state()
    # restored SCP state serves the last slot's envelopes to peers
    assert node2.herder.get_scp_state(0)
    sim2.start_all_nodes()
    resumed_from = node2.lcl
    assert sim2.crank_until_ledger(resumed_from + 3, timeout=120)
    # the chain continued from the persisted LCL, no fork
    got = db.load_header_by_seq(resumed_from + 1)
    assert got is not None
    assert got[1].previousLedgerHash == lcl_hash or resumed_from > lcl_seq


def test_crash_mid_checkpoint_republishes_after_restart(tmp_path):
    """Close past ledgers into a checkpoint window, crash before the
    boundary, restart, keep closing: the published checkpoint must contain
    ALL ledgers (including pre-crash ones) and a fresh node must be able to
    catch up from the archive to the exact LCL hash."""
    from stellar_core_tpu.catchup.catchup import CatchupManager
    from stellar_core_tpu.history.archive import (CHECKPOINT_FREQUENCY,
                                                  FileHistoryArchive)
    from stellar_core_tpu.history.manager import HistoryManager

    db_path = str(tmp_path / "node.db")
    bdir = BucketDir(str(tmp_path / "buckets"))
    archive = FileHistoryArchive(str(tmp_path / "archive"))

    mgr = LedgerManager(NID, invariant_manager=None)
    mgr.start_new_ledger()
    db = Database(db_path)
    mgr.enable_persistence(db, bdir)
    hm = HistoryManager(mgr, NID.hex(), [archive], database=db)
    root = _root_of(mgr)
    dest = SecretKey(b"\x23" * 32)
    dest_id = X.AccountID.ed25519(dest.public_key.ed25519)
    hm.ledger_closed(mgr.close_ledger(
        [root.tx([create_account_op(dest_id, 10**12)])], 1000))
    native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None)
    while mgr.last_closed_ledger_seq < CHECKPOINT_FREQUENCY - 5:
        hm.ledger_closed(mgr.close_ledger(
            [root.tx([payment_op(dest_id, native, 1000)])], 1001))
    db.close()
    del mgr, hm  # crash before the checkpoint boundary

    db = Database(db_path)
    mgr2 = LedgerManager.load_last_known_ledger(
        NID, db, bdir, invariant_manager=None)
    hm2 = HistoryManager(mgr2, NID.hex(), [archive], database=db)
    root2 = _root_of(mgr2)
    while not archive.get_state():
        hm2.ledger_closed(mgr2.close_ledger(
            [root2.tx([payment_op(dest_id, native, 1000)])], 1002))
    assert archive.get_state().current_ledger == CHECKPOINT_FREQUENCY - 1

    cm = CatchupManager(NID, NID.hex())
    fresh = cm.catchup_complete(archive)
    assert fresh.lcl_hash == (
        db.load_header_by_seq(CHECKPOINT_FREQUENCY - 1)[0])
