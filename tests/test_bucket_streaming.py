"""BucketListDB phase 2: streaming decode-free merges + disk-resident
levels (ISSUE 3).

Coverage: randomized differential merge_buckets vs merge_buckets_raw
(CAP-20 INIT/LIVE/DEAD collisions, both keep_tombstones modes, old/new
protocol versions — byte-identical records and hashes), the decode-free
guarantee (disk-resident inputs merge without any rehydration), residency
enforcement across live closes / catchup assume, and the RSS regression
guard: a multi-checkpoint replay with default residency keeps the peak
decoded-entry count bounded while bucket-list hashes stay identical to
the all-resident run.

Reference model: src/bucket/BucketBase.cpp merge streaming XDR records
between BucketInputIterator/BucketOutputIterator; src/bucket/test/
BucketTests.cpp merge cases.
"""

import random

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.bucket import (DEFAULT_RESIDENT_LEVELS, NUM_LEVELS,
                                     Bucket, BucketList, BucketListStore,
                                     merge_buckets, merge_buckets_raw)
from stellar_core_tpu.catchup.catchup import CatchupManager
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.history.archive import FileHistoryArchive
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.simulation.loadgen import LoadGenerator
from stellar_core_tpu.testutils import (TestAccount, create_account_op,
                                        native_payment_op, network_id)

PASSPHRASE = "bucket streaming test network"
NID = network_id(PASSPHRASE)


def _acct_entry(i, bal=10 ** 9):
    sk = SecretKey(bytes([i % 251 + 1]) * 31 + bytes([i // 251]))
    acc = X.AccountEntry(
        accountID=X.AccountID.ed25519(sk.public_key.ed25519),
        balance=bal, seqNum=1)
    return X.LedgerEntry(lastModifiedLedgerSeq=1,
                         data=X.LedgerEntryData.account(acc))


def _rand_bucket(rng, proto, universe=48, max_keys=30):
    """A random CAP-20 bucket: each key INIT, LIVE or DEAD — drawn from a
    small universe so merge chains hit every equal-key pair rule."""
    ids = rng.sample(range(1, universe + 1), rng.randrange(1, max_keys))
    init, live, dead = [], [], []
    for i in ids:
        c = rng.randrange(3)
        if c == 0:
            init.append(_acct_entry(i, rng.randrange(1, 10 ** 9)))
        elif c == 1:
            live.append(_acct_entry(i, rng.randrange(1, 10 ** 9)))
        else:
            dead.append(X.ledger_entry_key(_acct_entry(i)))
    return Bucket.fresh(proto, init, live, dead)


def _assert_identical(mem: Bucket, raw: Bucket):
    assert mem.hash() == raw.hash()
    assert mem.serialize() == raw.serialize()
    assert mem.protocol_version == raw.protocol_version
    assert len(mem) == len(raw)


# --- differential: merge_buckets vs merge_buckets_raw ----------------------

@pytest.mark.parametrize("seed,proto", [(1, 11), (2, 23), (3, 23)])
def test_raw_merge_differential_randomized(tmp_path, seed, proto):
    """ISSUE 3 acceptance: byte-identical output records and hashes across
    random CAP-20 pair sequences, both tombstone modes, old/new protocol
    versions — including chains where the raw output (disk-resident)
    feeds the next merge."""
    rng = random.Random(seed)
    store = BucketListStore(str(tmp_path))
    for _ in range(12):
        old, new = _rand_bucket(rng, proto), _rand_bucket(rng, proto)
        for kt in (True, False):
            mem = merge_buckets(old, new, kt)
            raw = merge_buckets_raw(old, new, kt, None, store)
            _assert_identical(mem, raw)
            # chain: the disk-resident output is the next merge's old side
            nxt = _rand_bucket(rng, proto)
            _assert_identical(merge_buckets(mem, nxt, kt),
                              merge_buckets_raw(raw, nxt, kt, None, store))


def test_raw_merge_mixed_protocols_and_explicit_version(tmp_path):
    rng = random.Random(9)
    store = BucketListStore(str(tmp_path))
    old = _rand_bucket(rng, 11)
    new = _rand_bucket(rng, 23)
    _assert_identical(merge_buckets(old, new, True),
                      merge_buckets_raw(old, new, True, None, store))
    _assert_identical(merge_buckets(old, new, False, protocol_version=17),
                      merge_buckets_raw(old, new, False, 17, store))


def test_raw_merge_empty_and_annihilation(tmp_path):
    """Empty inputs and all-annihilated outputs behave exactly like the
    in-memory merge (incl. the output protocol of an empty result)."""
    store = BucketListStore(str(tmp_path))
    e = Bucket.empty()
    b = _rand_bucket(random.Random(5), 23)
    for kt in (True, False):
        _assert_identical(merge_buckets(e, e, kt),
                          merge_buckets_raw(e, e, kt, None, store))
        _assert_identical(merge_buckets(e, b, kt),
                          merge_buckets_raw(e, b, kt, None, store))
        _assert_identical(merge_buckets(b, e, kt),
                          merge_buckets_raw(b, e, kt, None, store))
    # INIT annihilated by DEAD end-to-end: an INIT-only bucket merged with
    # its own tombstones is empty — and carries the merge protocol
    entries = [_acct_entry(i) for i in range(1, 9)]
    inits = Bucket.fresh(23, entries, [], [])
    deads = Bucket.fresh(23, [], [],
                         [X.ledger_entry_key(e) for e in entries])
    mem = merge_buckets(inits, deads, True)
    raw = merge_buckets_raw(inits, deads, True, None, store)
    assert mem.is_empty() and raw.is_empty()
    _assert_identical(mem, raw)


@pytest.mark.slow
def test_raw_merge_differential_deep_randomized(tmp_path):
    """Long random merge chains (the level lineage shape): fold 40 random
    buckets both ways, alternating tombstone modes like the real list's
    bottom level."""
    rng = random.Random(1234)
    store = BucketListStore(str(tmp_path))
    for proto in (11, 23):
        mem = Bucket.empty()
        raw = Bucket.empty()
        for step in range(40):
            nxt = _rand_bucket(rng, proto, universe=120, max_keys=60)
            kt = step % 5 != 4
            mem = merge_buckets(mem, nxt, kt)
            raw = merge_buckets_raw(raw, nxt, kt, None, store)
            _assert_identical(mem, raw)


# --- decode-free guarantee --------------------------------------------------

def test_raw_merge_is_decode_free(tmp_path, monkeypatch):
    """ISSUE 3 acceptance: a streaming merge over disk-resident inputs
    never constructs BucketEntry objects — rehydration is forbidden for
    the whole merge and the output stays disk-resident."""
    rng = random.Random(21)
    store = BucketListStore(str(tmp_path))
    old = merge_buckets_raw(_rand_bucket(rng, 23), _rand_bucket(rng, 23),
                            True, None, store)
    new = merge_buckets_raw(_rand_bucket(rng, 23), _rand_bucket(rng, 23),
                            True, None, store)
    assert old.is_disk_resident() and new.is_disk_resident()

    def forbidden(self):
        raise AssertionError("raw merge rehydrated a bucket")

    monkeypatch.setattr(Bucket, "_rehydrate", forbidden)
    out = merge_buckets_raw(old, new, True, None, store)
    assert out.is_disk_resident()
    assert out._entries is None and old._entries is None \
        and new._entries is None
    # ... and the result still matches the decoded merge byte for byte
    monkeypatch.undo()
    assert merge_buckets(old, new, True).serialize() == out.serialize()


# --- residency over live closes ---------------------------------------------

def _spin_up(store=None, n_accounts=24, **kw):
    mgr = LedgerManager(NID, bucket_store=store, entry_cache_size=64, **kw)
    mgr.start_new_ledger()
    sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.account_key_xdr(sk.public_key.ed25519))
    root = TestAccount(mgr, sk, e.data.value.seqNum)
    sks = [SecretKey(bytes([i + 1]) * 32) for i in range(n_accounts)]
    mgr.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(s.public_key.ed25519), 10 ** 11)
        for s in sks])], 1000)
    accounts = []
    for s in sks:
        ent = mgr.root.get_entry(X.account_key_xdr(s.public_key.ed25519))
        accounts.append(TestAccount(mgr, s, ent.data.value.seqNum))
    return mgr, root, accounts


def _traffic(mgr, accounts, n_ledgers, seed=3):
    rng = random.Random(seed)
    hashes = []
    for i in range(n_ledgers):
        frames = []
        for _ in range(4):
            src = accounts[rng.randrange(len(accounts))]
            dst = accounts[rng.randrange(len(accounts))]
            frames.append(src.tx([native_payment_op(
                dst.account_id, 500 + rng.randrange(10 ** 5))]))
        mgr.close_ledger(frames, 4000 + 5 * i)
        hashes.append(mgr.lcl_hash)
    return hashes


def test_deep_levels_go_disk_resident_with_identical_hashes(tmp_path):
    """Enough closes to populate levels >= the residency depth: those
    buckets drop their decoded lists, per-ledger hashes stay identical to
    the in-memory run, and reads still serve."""
    mem_mgr, _, mem_accounts = _spin_up()
    mem_hashes = _traffic(mem_mgr, mem_accounts, 40)

    store = BucketListStore(str(tmp_path))
    mgr, _, accounts = _spin_up(store=store)
    hashes = _traffic(mgr, accounts, 40)
    assert hashes == mem_hashes

    bl = mgr.bucket_list
    assert bl.resident_levels == DEFAULT_RESIDENT_LEVELS
    deep_nonempty = 0
    for i in range(bl.resident_levels, NUM_LEVELS):
        for b in (bl.levels[i].curr, bl.levels[i].snap):
            if not b.is_empty():
                deep_nonempty += 1
                assert b.is_disk_resident()
    assert deep_nonempty > 0, "traffic never reached a disk level"
    # decoded entries are bounded by the resident buckets (4: levels 0-1
    # curr+snap, each at most one record per live key) + one close's batch
    assert bl.decoded_entry_count() <= 4 * mem_mgr.root.entry_count() + 60
    # point reads through the root still resolve deep-level entries
    kb = X.account_key_xdr(accounts[0].secret.public_key.ed25519)
    assert mgr.root.get_entry(kb).data.value.balance == \
        mem_mgr.root.get_entry(kb).data.value.balance


def test_resident_levels_config_surface():
    cfg = Config.from_dict({"BUCKET_RESIDENT_LEVELS": 4})
    assert cfg.BUCKET_RESIDENT_LEVELS == 4
    assert Config().BUCKET_RESIDENT_LEVELS == DEFAULT_RESIDENT_LEVELS
    bl = BucketList()
    assert bl.resident_levels == NUM_LEVELS     # unconfigured: no eviction


# --- multi-checkpoint replay: RSS guard + hash identity ---------------------

@pytest.fixture(scope="module")
def published(tmp_path_factory):
    """A multi-checkpoint synthetic chain with enough distinct accounts
    that deep levels carry real weight."""
    archive_dir = tmp_path_factory.mktemp("stream-archive")
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(archive_dir))
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=17)
    gen.create_accounts(40, per_ledger=10)
    gen.payment_ledgers(30, txs_per_ledger=6)
    gen.run_to_checkpoint_boundary()
    while len(history.published_checkpoints) < 2:
        gen.payment_ledgers(10, txs_per_ledger=6)
        gen.run_to_checkpoint_boundary()
    return archive, mgr


def test_rss_guard_replay_bounded_and_hash_identical(published, tmp_path):
    """ISSUE 3 acceptance: with BUCKET_RESIDENT_LEVELS at its default a
    multi-checkpoint replay's peak decoded-entry count stays under a
    fixed bound, strictly below the all-resident run's, while disk and
    all-resident bucket-list hashes are identical."""
    archive, live = published

    def replay(subdir, resident_levels):
        store = BucketListStore(str(tmp_path / subdir))
        cm = CatchupManager(NID, PASSPHRASE, native=False,
                            bucket_store=store, entry_cache_size=32,
                            resident_levels=resident_levels)
        return cm.catchup_complete(archive)

    m_on = replay("resident-default", None)            # default depth
    m_off = replay("resident-all", NUM_LEVELS)         # eviction disabled
    assert m_on.lcl_hash == m_off.lcl_hash == live.lcl_hash
    assert m_on.bucket_list.hash() == m_off.bucket_list.hash() \
        == live.bucket_list.hash()

    peak_on = m_on.bucket_list.peak_decoded_entries
    peak_off = m_off.bucket_list.peak_decoded_entries
    total = m_off.root.entry_count()
    assert peak_on > 0 and peak_off >= total
    # the memory story: peak bounded by the top levels + one close's batch,
    # not by the ledger.  The load above is deterministic (fixed seeds);
    # ~1.5x headroom over the measured 164 absorbs load-shape drift.
    assert peak_on <= 250, (peak_on, peak_off)
    assert peak_on < peak_off
    # end-state: deep levels hold zero decoded entries
    bl = m_on.bucket_list
    for i in range(bl.resident_levels, NUM_LEVELS):
        assert bl.levels[i].curr.resident_entry_count() == 0
        assert bl.levels[i].snap.resident_entry_count() == 0


def test_assume_state_enforces_residency(published, tmp_path):
    """catchup_minimal (ApplyBucketsWork analog): deep-level buckets
    assumed from the archive drop their decoded lists; entry reads and
    counts match the in-memory assume."""
    archive, _ = published
    store = BucketListStore(str(tmp_path))
    cm = CatchupManager(NID, PASSPHRASE, bucket_store=store,
                        entry_cache_size=32)
    m = cm.catchup_minimal(archive)
    m_mem = CatchupManager(NID, PASSPHRASE).catchup_minimal(archive)
    assert m.lcl_hash == m_mem.lcl_hash
    assert m.root.entry_count() == m_mem.root.entry_count()
    bl = m.bucket_list
    deep = [b for i in range(bl.resident_levels, NUM_LEVELS)
            for b in (bl.levels[i].curr, bl.levels[i].snap)
            if not b.is_empty()]
    assert deep and all(b.is_disk_resident() for b in deep)
    for kb in list(m_mem.root.all_keys())[:15]:
        assert m.root.get_entry(kb).to_xdr() == \
            m_mem.root.get_entry(kb).to_xdr()


def test_streaming_merge_metrics_recorded(tmp_path):
    """Observability contract: streaming merges record bucket.merge.stream
    timings and bucket.merge.bytes volume; the resident-entry gauge is
    live."""
    from stellar_core_tpu.util.metrics import registry
    store = BucketListStore(str(tmp_path))
    mgr, _, accounts = _spin_up(store=store)
    _traffic(mgr, accounts, 40)
    snap = registry().snapshot(prefix="bucket.")
    assert snap.get("bucket.merge.stream", {}).get("count", 0) > 0
    assert snap.get("bucket.merge.bytes", {}).get("count", 0) > 0
    gauge = snap.get("bucket.resident.entries")
    assert gauge is not None and gauge["value"] is not None
    assert gauge["value"] == mgr.bucket_list.decoded_entry_count()
