"""Native XDR serializer (native/cxdr.c) differential tests.

The C pack path must produce byte-identical output — and equivalent
rejections — to the pure-Python codec for every schema shape: primitives,
enums, opaques, strings, arrays, optionals, structs, unions (incl. void
arms, default arms and recursive forward refs).
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.xdr import codec as C

pytestmark = pytest.mark.skipif(
    C._cxdr is None, reason="native _cxdr not built (make native)")


def _both(adapter, val):
    return adapter.pack(val), adapter._pack_py(val)


def _sample_values():
    sk = b"\x07" * 32
    yield X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(sk)))
    yield X.Price(n=3, d=7)
    yield X.Asset.native()
    yield X.Asset.alphaNum4(X.AlphaNum4(
        assetCode=b"EUR\x00", issuer=X.AccountID.ed25519(sk)))
    yield X.StellarValue(txSetHash=b"\x01" * 32, closeTime=2**40)
    yield X.SCPQuorumSet(
        threshold=2,
        validators=[X.NodeID.ed25519(bytes([i]) * 32) for i in range(3)],
        innerSets=[X.SCPQuorumSet(
            threshold=1,
            validators=[X.NodeID.ed25519(b"\x09" * 32)])])
    yield X.ClaimPredicate.andPredicates([
        X.ClaimPredicate.unconditional(),
        X.ClaimPredicate.notPredicate(
            X.ClaimPredicate.absBefore(123456789))])
    yield X.Memo.text(b"hello world")
    yield X.StellarMessage.getPeers()
    yield X.Hello(
        ledgerVersion=23, overlayVersion=38, overlayMinVersion=35,
        networkID=b"\x01" * 32, versionStr=b"x" * 99, listeningPort=-1,
        peerID=X.NodeID.ed25519(b"\x02" * 32),
        cert=X.AuthCert(pubkey=X.Curve25519Public(key=b"\x03" * 32),
                        expiration=0, sig=b""),
        nonce=b"\x05" * 32)
    yield X.TransactionResult(
        feeCharged=100,
        result=X.TransactionResultResult(
            X.TransactionResultCode.txNOT_SUPPORTED, None),
        ext=X.TransactionResultExt(0, None))


@pytest.mark.parametrize("val", list(_sample_values()),
                         ids=lambda v: type(v).__name__)
def test_pack_identical_to_python(val):
    native, py = _both(type(val)._xdr_adapter(), val)
    assert native == py
    # and the bytes round-trip through the Python decoder
    assert type(val).from_xdr(native) == val


def test_whole_ledger_close_identical(tmp_path):
    """End-to-end: a ledger closed with the native serializer hashes
    identically to one closed with the pure-Python path."""
    import subprocess
    import sys
    import os
    code = """
import sys
sys.path.insert(0, %r)
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.testutils import TestAccount, create_account_op, network_id
from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
m = LedgerManager(network_id("cxdr diff net"))
m.start_new_ledger()
sk = m.root_account_secret()
e = m.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
    accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
root = TestAccount(m, sk, e.data.value.seqNum)
m.close_ledger([root.tx([create_account_op(
    X.AccountID.ed25519(SecretKey(b"\\x44" * 32).public_key.ed25519),
    10**10)])], 1000)
print(m.lcl_hash.hex())
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hashes = {}
    for label, env_extra in (("native", {}),
                             ("python", {"STELLAR_TPU_NO_CXDR": "1"})):
        env = dict(os.environ, **env_extra)
        env.pop("PYTEST_CURRENT_TEST", None)
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        hashes[label] = out.stdout.strip().splitlines()[-1]
    assert hashes["native"] == hashes["python"]


def test_rejections_match():
    price_t = X.Price._xdr_adapter()
    for bad in (X.Price(n=2**31, d=1), X.Price(n=1, d=-2**31 - 1)):
        with pytest.raises(X.XdrError):
            price_t.pack(bad)
        with pytest.raises(X.XdrError):
            bytes_out = bytearray()
            price_t.pack_into(bad, bytes_out)
    # fixed opaque wrong length
    with pytest.raises(X.XdrError):
        X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(b"\x01" * 31))).to_xdr()
    # bad enum member
    with pytest.raises(X.XdrError):
        X.Memo(99999, None).to_xdr()


def test_strictness_parity_with_python():
    """The three divergences a review once found must stay fixed: default-arm
    unions reject non-member discriminants, wrong-typed values reject, and
    str is not accepted for opaque fields."""
    with pytest.raises(X.XdrError):
        X.TransactionResultResult(999999, None).to_xdr()

    class Fake:
        n, d = 1, 2

    with pytest.raises(X.XdrError):
        X.Price._xdr_adapter().pack(Fake())
    with pytest.raises(X.XdrError):
        C.Opaque(5).pack("hello")


@pytest.mark.parametrize("val", list(_sample_values()),
                         ids=lambda v: type(v).__name__)
def test_unpack_identical_to_python(val):
    """Native unpack must reproduce the Python decoder's objects exactly —
    including enum members (not bare ints) for enum fields/switches."""
    adapter = type(val)._xdr_adapter()
    blob = adapter._pack_py(val)
    native = C._cxdr.unpack(adapter._cxdr_prog
                            or C.compile_program(adapter), blob)
    py, off = adapter.unpack_from(blob, 0)
    assert off == len(blob)
    assert native == py == val
    if hasattr(val, "switch"):
        assert type(native.switch) is type(val.switch)


def test_unpack_from_fast_streams():
    """Sequential stream decode (the bucket/catchup pattern)."""
    vals = [X.Price(n=i, d=i + 1) for i in range(50)]
    adapter = X.Price._xdr_adapter()
    blob = b"".join(adapter.pack(v) for v in vals)
    off = 0
    out = []
    while off < len(blob):
        v, off = adapter.unpack_from_fast(blob, off)
        out.append(v)
    assert out == vals


def test_unpack_rejections_match_python():
    """Mutated bytes must be accepted/rejected identically by the native
    and Python decoders, and accepted values must be equal (the fuzz
    differential that guards hash integrity)."""
    import random
    from stellar_core_tpu.fuzz import mutate_bytes, random_xdr_value

    rng = random.Random(99)
    roots = [X.TransactionEnvelope, X.LedgerEntry, X.StellarMessage,
             X.LedgerHeader, X.BucketEntry]
    checked = 0
    for i in range(300):
        cls = rng.choice(roots)
        val = random_xdr_value(cls, rng)
        try:
            blob = val.to_xdr()
        except X.XdrError:
            continue
        adapter = cls._xdr_adapter()
        mut = mutate_bytes(blob, rng)
        native_err = py_err = None
        native_val = py_val = None
        try:
            native_val = C._cxdr.unpack(adapter._cxdr_prog, mut)
        except C._cxdr.Error as e:
            native_err = True
        try:
            py_val, off = adapter.unpack_from(mut, 0)
            if off != len(mut):
                raise X.XdrError("trailing")
        except (X.XdrError, OverflowError):
            py_err = True
        assert bool(native_err) == bool(py_err), \
            f"case {i}: native={native_err} py={py_err}"
        if native_err is None:
            assert native_val == py_val
        checked += 1
    assert checked > 100


def test_hostile_array_length_rejected_without_allocation():
    """A 4-byte wire length claiming 2^32-ish elements must fail as
    XdrError before any preallocation (regression: bare MemoryError)."""
    import struct
    adapter = X.TransactionSet._xdr_adapter()
    blob = b"\x11" * 32 + struct.pack(">I", 0xFFFFFFF0)
    with pytest.raises(X.XdrError):
        adapter.unpack(blob)


@pytest.mark.parametrize("val", list(_sample_values()),
                         ids=lambda v: type(v).__name__)
def test_deep_copy_identical_to_python(val):
    """Native deep_copy must structurally equal the value and the pure-
    Python copy, with full mutation isolation of the mutable spine."""
    native = C._cxdr.deep_copy(val)
    py = C._deep_copy_py(val)
    adapter = type(val)._xdr_adapter()
    assert adapter.pack(native) == adapter.pack(py) == adapter.pack(val)
    assert native is not val


def test_deep_copy_mutation_isolation():
    qs = X.SCPQuorumSet(
        threshold=2,
        validators=[X.NodeID.ed25519(bytes([i]) * 32) for i in range(3)],
        innerSets=[X.SCPQuorumSet(
            threshold=1, validators=[X.NodeID.ed25519(b"\x09" * 32)])])
    cp = C._cxdr.deep_copy(qs)
    cp.threshold = 99
    cp.validators.pop()
    cp.innerSets[0].threshold = 42
    assert qs.threshold == 2
    assert len(qs.validators) == 3
    assert qs.innerSets[0].threshold == 1


def test_deep_copy_shares_immutable_leaves():
    # bytes/enum leaves are immutable — sharing them is the point
    key = X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(b"\x07" * 32)))
    cp = C._cxdr.deep_copy(key)
    assert cp.value.accountID.value is key.value.accountID.value
    assert cp.switch is key.switch
