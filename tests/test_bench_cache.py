"""bench.py last-good result cache (VERDICT r3 weak #1): a tunnel outage
at driver time must degrade to aged, stale-flagged last-good numbers —
never to a 0.0 record while evidence exists."""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.CACHE_PATH = str(tmp_path / "BENCH_CACHE.json")
    return mod


def test_degraded_report_empty_cache(bench):
    rep = bench._degraded_report("down")
    assert rep["value"] == 0.0 and rep["vs_baseline"] == 0.0
    assert rep["extra"]["stale"] is True
    assert "no BENCH_CACHE.json" in rep["extra"]["detail"]


def test_cache_roundtrip_and_staleness(bench):
    bench._cache_put("sigs", {"ed25519_tpu_sigs_per_sec": 50000.0,
                              "ed25519_libsodium_1core_sigs_per_sec": 12500.0,
                              "note": "sig note"})
    bench._cache_put("replay", {"replay_accel_vs_cpu": 1.2, "note": "r note"})
    bench._cache_put("quorum", {"quorum_asym5_tpu_s": 9.9})
    # the persisted file is well-formed json with timestamps
    with open(bench.CACHE_PATH) as f:
        disk = json.load(f)
    assert set(disk) == {"sigs", "replay", "quorum"}
    assert all("measured_at_unix" in v for v in disk.values())

    rep = bench._degraded_report("tunnel wedged")
    assert rep["value"] == 50000.0
    assert rep["vs_baseline"] == 4.0
    e = rep["extra"]
    assert e["stale"] is True and e["accel_unavailable"] is True
    assert e["replay_accel_vs_cpu"] == 1.2
    assert e["quorum_asym5_tpu_s"] == 9.9
    # per-section notes must not clobber each other
    assert e["sigs_note"] == "sig note" and e["replay_note"] == "r note"
    for s in ("sigs", "replay", "quorum"):
        assert e[f"{s}_age_hours"] >= 0.0
        assert e[f"{s}_measured_at"]


def test_cache_put_overwrites_section(bench):
    bench._cache_put("sigs", {"ed25519_tpu_sigs_per_sec": 1.0,
                              "ed25519_libsodium_1core_sigs_per_sec": 1.0})
    bench._cache_put("sigs", {"ed25519_tpu_sigs_per_sec": 2.0,
                              "ed25519_libsodium_1core_sigs_per_sec": 1.0})
    assert bench._degraded_report("x")["value"] == 2.0


def test_cache_write_failure_is_nonfatal(bench):
    bench.CACHE_PATH = "/nonexistent-dir/deep/x.json"
    bench._cache_put("sigs", {"a": 1})   # must not raise


def test_low_deadline_exits_zero_with_json_line(tmp_path):
    """ISSUE 5 satellite: the global deadline must actually bound the run
    — BENCH_r05 still hit rc=124 with the tail cut mid-replay.  With a
    deadline too small for any accelerated section, bench.py must skip
    everything skippable, ALWAYS print its one JSON line, and exit 0."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BENCH_DEADLINE_S="1", JAX_PLATFORMS="cpu",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True, timeout=300, env=env, cwd=str(tmp_path))
    assert r.returncode == 0, (r.returncode, r.stderr[-2000:])
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert lines, r.stdout
    doc = json.loads(lines[-1])
    assert doc["metric"] == "ed25519_batch_verify_throughput"
    extra = doc["extra"]
    assert extra["bench_budget_s"] == 1.0
    # every device-side section degraded to an explicit skip marker
    for section in ("sigs", "replay", "quorum"):
        assert str(extra.get(section, "")).startswith("SKIPPED"), \
            (section, extra.get(section))


def test_replay_rounds_preempted_by_deadline(bench):
    """bench_replay stops scheduling further (cpu, accel) rounds once the
    measured per-round cost no longer fits the global budget — the
    mid-section pre-emption BENCH_r05 was missing.  Driven with stubbed
    replay passes (no device)."""
    calls = {"n": 0}

    class _FakeMgr:
        lcl_hash = b"h"

        def offload_hit_rate(self):
            return 0.5

    class _FakeCM:
        def __init__(self, *a, **kw):
            self.stats = {}

        def catchup_complete(self, archive, to_ledger=None):
            calls["n"] += 1
            return _FakeMgr()

        def offload_hit_rate(self):
            return 0.5

    class _FakeArchive:
        def get_state(self):
            class _S:
                current_ledger = 100
            return _S()

    import stellar_core_tpu.catchup.catchup as cc
    orig = cc.CatchupManager
    cc.CatchupManager = _FakeCM
    try:
        # budget large enough for round 1, then exhausted: rounds 2 and 3
        # must be pre-empted, partial medians returned
        left = [1000.0, 0.0, 0.0, 0.0]
        out = bench.bench_replay(b"\0" * 32, "net", _FakeArchive(), b"h",
                                 rounds=3,
                                 time_left_fn=lambda: left.pop(0)
                                 if left else 0.0)
    finally:
        cc.CatchupManager = orig
    assert out is not None
    cpu_rate, tpu_rate, hit_rate, n_ledgers, phases = out
    assert phases["rounds_skipped_budget"] == 2
    assert len(phases["cpu_rates"]) == 1
    # warm pass + one (cpu, accel) round = 3 catchup_complete calls
    assert calls["n"] == 3
