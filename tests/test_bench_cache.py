"""bench.py last-good result cache (VERDICT r3 weak #1): a tunnel outage
at driver time must degrade to aged, stale-flagged last-good numbers —
never to a 0.0 record while evidence exists."""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.CACHE_PATH = str(tmp_path / "BENCH_CACHE.json")
    return mod


def test_degraded_report_empty_cache(bench):
    rep = bench._degraded_report("down")
    assert rep["value"] == 0.0 and rep["vs_baseline"] == 0.0
    assert rep["extra"]["stale"] is True
    assert "no BENCH_CACHE.json" in rep["extra"]["detail"]


def test_cache_roundtrip_and_staleness(bench):
    bench._cache_put("sigs", {"ed25519_tpu_sigs_per_sec": 50000.0,
                              "ed25519_libsodium_1core_sigs_per_sec": 12500.0,
                              "note": "sig note"})
    bench._cache_put("replay", {"replay_accel_vs_cpu": 1.2, "note": "r note"})
    bench._cache_put("quorum", {"quorum_asym5_tpu_s": 9.9})
    # the persisted file is well-formed json with timestamps
    with open(bench.CACHE_PATH) as f:
        disk = json.load(f)
    assert set(disk) == {"sigs", "replay", "quorum"}
    assert all("measured_at_unix" in v for v in disk.values())

    rep = bench._degraded_report("tunnel wedged")
    assert rep["value"] == 50000.0
    assert rep["vs_baseline"] == 4.0
    e = rep["extra"]
    assert e["stale"] is True and e["accel_unavailable"] is True
    assert e["replay_accel_vs_cpu"] == 1.2
    assert e["quorum_asym5_tpu_s"] == 9.9
    # per-section notes must not clobber each other
    assert e["sigs_note"] == "sig note" and e["replay_note"] == "r note"
    for s in ("sigs", "replay", "quorum"):
        assert e[f"{s}_age_hours"] >= 0.0
        assert e[f"{s}_measured_at"]


def test_cache_put_overwrites_section(bench):
    bench._cache_put("sigs", {"ed25519_tpu_sigs_per_sec": 1.0,
                              "ed25519_libsodium_1core_sigs_per_sec": 1.0})
    bench._cache_put("sigs", {"ed25519_tpu_sigs_per_sec": 2.0,
                              "ed25519_libsodium_1core_sigs_per_sec": 1.0})
    assert bench._degraded_report("x")["value"] == 2.0


def test_cache_write_failure_is_nonfatal(bench):
    bench.CACHE_PATH = "/nonexistent-dir/deep/x.json"
    bench._cache_put("sigs", {"a": 1})   # must not raise
