"""bench.py last-good result cache (VERDICT r3 weak #1): a tunnel outage
at driver time must degrade to aged, stale-flagged last-good numbers —
never to a 0.0 record while evidence exists."""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.CACHE_PATH = str(tmp_path / "BENCH_CACHE.json")
    return mod


def test_degraded_report_empty_cache(bench):
    rep = bench._degraded_report("down")
    assert rep["value"] == 0.0 and rep["vs_baseline"] == 0.0
    assert rep["extra"]["stale"] is True
    assert "no BENCH_CACHE.json" in rep["extra"]["detail"]


def test_cache_roundtrip_and_staleness(bench):
    bench._cache_put("sigs", {"ed25519_tpu_sigs_per_sec": 50000.0,
                              "ed25519_libsodium_1core_sigs_per_sec": 12500.0,
                              "note": "sig note"})
    bench._cache_put("replay", {"replay_accel_vs_cpu": 1.2, "note": "r note"})
    bench._cache_put("quorum", {"quorum_asym5_tpu_s": 9.9})
    # the persisted file is well-formed json with timestamps
    with open(bench.CACHE_PATH) as f:
        disk = json.load(f)
    assert set(disk) == {"sigs", "replay", "quorum"}
    assert all("measured_at_unix" in v for v in disk.values())

    rep = bench._degraded_report("tunnel wedged")
    assert rep["value"] == 50000.0
    assert rep["vs_baseline"] == 4.0
    e = rep["extra"]
    assert e["stale"] is True and e["accel_unavailable"] is True
    assert e["replay_accel_vs_cpu"] == 1.2
    assert e["quorum_asym5_tpu_s"] == 9.9
    # per-section notes must not clobber each other
    assert e["sigs_note"] == "sig note" and e["replay_note"] == "r note"
    for s in ("sigs", "replay", "quorum"):
        assert e[f"{s}_age_hours"] >= 0.0
        assert e[f"{s}_measured_at"]


def test_cache_put_overwrites_section(bench):
    bench._cache_put("sigs", {"ed25519_tpu_sigs_per_sec": 1.0,
                              "ed25519_libsodium_1core_sigs_per_sec": 1.0})
    bench._cache_put("sigs", {"ed25519_tpu_sigs_per_sec": 2.0,
                              "ed25519_libsodium_1core_sigs_per_sec": 1.0})
    assert bench._degraded_report("x")["value"] == 2.0


def test_cache_write_failure_is_nonfatal(bench):
    bench.CACHE_PATH = "/nonexistent-dir/deep/x.json"
    bench._cache_put("sigs", {"a": 1})   # must not raise


def test_low_deadline_exits_zero_with_json_line(tmp_path):
    """ISSUE 5 satellite: the global deadline must actually bound the run
    — BENCH_r05 still hit rc=124 with the tail cut mid-replay.  With a
    deadline too small for any accelerated section, bench.py must skip
    everything skippable, ALWAYS print its one JSON line, and exit 0."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BENCH_DEADLINE_S="1", JAX_PLATFORMS="cpu",
               BENCH_CACHE_PATH=str(tmp_path / "cache.json"))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True, timeout=300, env=env, cwd=str(tmp_path))
    assert r.returncode == 0, (r.returncode, r.stderr[-2000:])
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert lines, r.stdout
    doc = json.loads(lines[-1])
    assert doc["metric"] == "ed25519_batch_verify_throughput"
    extra = doc["extra"]
    assert extra["bench_budget_s"] == 1.0
    # every device-side section degraded to an explicit skip marker, and
    # the CPU-side catchup_parallel section skipped under budget too
    for section in ("sigs", "replay", "quorum", "catchup_parallel"):
        assert str(extra.get(section, "")).startswith("SKIPPED"), \
            (section, extra.get(section))


def test_replay_rounds_preempted_by_deadline(bench):
    """bench_replay stops scheduling further (cpu, accel) rounds once the
    measured per-round cost no longer fits the global budget — the
    mid-section pre-emption BENCH_r05 was missing.  Driven with stubbed
    replay passes (no device)."""
    calls = {"n": 0}

    class _FakeMgr:
        lcl_hash = b"h"

        def offload_hit_rate(self):
            return 0.5

    class _FakeCM:
        def __init__(self, *a, **kw):
            self.stats = {}

        def catchup_complete(self, archive, to_ledger=None):
            calls["n"] += 1
            return _FakeMgr()

        def offload_hit_rate(self):
            return 0.5

    class _FakeArchive:
        def get_state(self):
            class _S:
                current_ledger = 100
            return _S()

    import stellar_core_tpu.catchup.catchup as cc
    orig = cc.CatchupManager
    cc.CatchupManager = _FakeCM
    try:
        # budget large enough for round 1, then exhausted: rounds 2 and 3
        # must be pre-empted, partial medians returned
        left = [1000.0, 0.0, 0.0, 0.0]
        out = bench.bench_replay(b"\0" * 32, "net", _FakeArchive(), b"h",
                                 rounds=3,
                                 time_left_fn=lambda: left.pop(0)
                                 if left else 0.0)
    finally:
        cc.CatchupManager = orig
    assert out is not None
    cpu_rate, tpu_rate, hit_rate, n_ledgers, phases = out
    assert phases["rounds_skipped_budget"] == 2
    assert len(phases["cpu_rates"]) == 1
    # warm pass + one (cpu, accel) round = 3 catchup_complete calls
    assert calls["n"] == 3


def test_quorum_cell_subprocess_roundtrip(bench):
    """One matrix cell runs in its own process and reports its wall-clock
    + verdict as a JSON line (the per-core pre-emption seam BENCH_r05's
    in-process rows were missing)."""
    cell = bench._run_quorum_cell("tier1", "contraction", timeout_s=120)
    assert cell.get("intersects") is True
    assert isinstance(cell["s"], float)


def test_quorum_cell_preempted_by_hard_timeout(bench):
    """An overrunning cell is KILLED, not waited out: even the cheapest
    row cannot finish inside a 0.05s bound, so the runner must report
    pre-emption instead of hanging or raising."""
    cell = bench._run_quorum_cell("tier1", "contraction", timeout_s=0.05)
    assert "preempted" in cell


def test_bench_quorum_skips_everything_when_global_deadline_spent(bench):
    """With the remaining global budget below the reporting reserve, every
    cell emits SKIPPED(budget) without spawning a single subprocess — the
    quorum analog of the replay rounds' pre-emption."""
    spawned = []
    bench._run_quorum_cell = lambda *a, **kw: spawned.append(a) or {}
    matrix = bench.bench_quorum(time_left_fn=lambda: 31.0, budget_s=700.0)
    assert not spawned
    rows = [v for k, v in matrix.items()
            if k.endswith("_s") and not k.startswith("quorum_matrix")]
    assert rows and all(str(v).startswith("SKIPPED") for v in rows)


def test_bench_quorum_records_preempted_cells_and_continues(bench):
    """A cell that blows past its estimate is pre-empted mid-run and
    recorded as a SKIPPED row; the section keeps going and returns its
    matrix instead of dying with the driver's rc=124."""
    bench._run_quorum_cell = lambda row, engine, timeout_s: \
        {"preempted": 9.9}
    matrix = bench.bench_quorum(time_left_fn=lambda: 10_000.0,
                                budget_s=700.0)
    assert matrix["tier1_contraction_s"] == \
        "SKIPPED(budget, pre-empted after 9.9s)"
    assert matrix["asym7_tpu_s"] == "SKIPPED(budget, pre-empted after 9.9s)"
    assert "quorum_matrix_spent_s" in matrix


def test_bench_quorum_cell_failure_is_a_row_not_a_crash(bench):
    """A cell subprocess that dies (tunnel crash inside the child) becomes
    a FAILED row; the matrix and the final JSON line still happen."""
    bench._run_quorum_cell = lambda row, engine, timeout_s: \
        {"failed": 1, "detail": "boom"}
    matrix = bench.bench_quorum(time_left_fn=lambda: 10_000.0,
                                budget_s=700.0)
    assert matrix["rings16_py_s"] == "FAILED(rc=1)"


def test_merge_last_good_preserves_measured_rows(bench):
    """A SKIPPED/FAILED row must not cache OVER a previously measured
    number — run A's asym7 measurement survives run B's pre-emption and
    is what a later degraded run stale-fills."""
    bench._cache_put("quorum", {"asym7_tpu_s": 255.0, "rings16_py_s": 0.2})
    merged = bench._merge_last_good("quorum", {
        "asym7_tpu_s": "SKIPPED(budget, pre-empted after 240s)",
        "rings16_py_s": 0.21,
        "asym6_c_s": "FAILED(rc=1)",
        "asym6_py_s": "SKIPPED(~180s, over per-row budget)",
    })
    assert merged["asym7_tpu_s"] == 255.0        # marker did not clobber
    assert merged["rings16_py_s"] == 0.21        # fresh number wins
    assert merged["asym6_c_s"] == "FAILED(rc=1)"  # nothing cached to keep
    assert merged["asym6_py_s"].startswith("SKIPPED")
    # provenance: the restored row carries the timestamp of the run that
    # MEASURED it (the section measured_at gets re-stamped on _cache_put)
    with open(bench.CACHE_PATH) as f:
        first_ts = json.load(f)["quorum"]["measured_at"]
    assert merged["restored_rows"] == {"asym7_tpu_s": first_ts}
    # ...and it chains: a third run restoring the same row keeps the
    # ORIGINAL timestamp, not the second run's
    bench._cache_put("quorum", merged)
    merged2 = bench._merge_last_good("quorum", {
        "asym7_tpu_s": "SKIPPED(budget)", "rings16_py_s": 0.22})
    assert merged2["asym7_tpu_s"] == 255.0
    assert merged2["restored_rows"] == {"asym7_tpu_s": first_ts}
