"""Overlay P2P tests: framing, auth handshake, flooding, item fetch, flow
control — over loopback (deterministic, virtual time) and real TCP sockets.

Reference test model: src/overlay/test/{OverlayManagerTests, PeerTests,
FloodTests, ItemFetcherTests, FlowControlTests}.cpp + LoopbackPeer.
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.herder.herder import Herder
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.overlay import (FrameDecoder, OverlayManager, Peer,
                                      PeerAuth, TCPTransport, frame_encode,
                                      make_loopback_pair)
from stellar_core_tpu.simulation.simulation import qset_of
from stellar_core_tpu.testutils import TestAccount, create_account_op, \
    network_id
from stellar_core_tpu.util.clock import ClockMode, VirtualClock

NID = network_id("overlay test net")

_LARGE_ENV = None


def _large_envelope():
    """A ~7KB signed 100-op envelope (cached — the tests only need bulk
    bytes that decode as a real TransactionEnvelope)."""
    global _LARGE_ENV
    if _LARGE_ENV is None:
        from stellar_core_tpu.testutils import build_tx, native_payment_op
        ops = [native_payment_op(X.AccountID.ed25519(b"\x44" * 32), 5)] * 100
        _LARGE_ENV = build_tx(NID, SecretKey(b"\x93" * 32), 1, ops).envelope
    return _LARGE_ENV


# ---------------------------------------------------------------------------
# framing

class TestFraming:
    def test_roundtrip_and_partial_feeds(self):
        d = FrameDecoder()
        f1 = frame_encode(b"hello")
        f2 = frame_encode(b"world!" * 100)
        stream = f1 + f2
        got = []
        for i in range(0, len(stream), 7):   # drip-feed 7 bytes at a time
            got.extend(d.feed(stream[i:i + 7]))
        assert got == [b"hello", b"world!" * 100]

    def test_rejects_fragmented_record(self):
        d = FrameDecoder()
        with pytest.raises(ValueError, match="fragmented"):
            d.feed((5).to_bytes(4, "big") + b"xxxxx")  # high bit clear

    def test_rejects_oversized(self):
        d = FrameDecoder()
        with pytest.raises(ValueError, match="oversized"):
            d.feed((0x80000000 | (64 * 1024 * 1024)).to_bytes(4, "big"))


# ---------------------------------------------------------------------------
# auth primitives

class TestFrameSplice:
    def test_spliced_authenticated_frame_matches_object_path(self):
        """_send_authenticated splices the AuthenticatedMessage bytes
        from the pre-encoded body (union arm + sequence + message + MAC)
        — must be byte-identical to building the object and encoding
        it."""
        import struct
        msg = X.StellarMessage.getPeers()
        body = msg.to_xdr()
        mac = b"\xab" * 32
        for seq in (0, 7, 2**40):
            am = X.AuthenticatedMessage.v0(X.AuthenticatedMessageV0(
                sequence=seq, message=msg,
                mac=X.HmacSha256Mac(mac=mac)))
            spliced = (b"\x00\x00\x00\x00" + struct.pack(">Q", seq)
                       + body + mac)
            assert am.to_xdr() == spliced
            # and the receiver's body slice inverts the splice
            assert spliced[12:len(spliced) - 32] == body


class TestPeerAuth:
    def _auth(self, seed, now=lambda: 1000):
        return PeerAuth(SecretKey(seed), NID, now, auth_seed=seed)

    def test_cert_mints_and_verifies(self):
        a = self._auth(b"\x01" * 32)
        b = self._auth(b"\x02" * 32)
        cert = a.get_cert()
        assert b.verify_remote_cert(cert,
                                    a.node_secret.public_key.ed25519)

    def test_cert_wrong_identity_rejected(self):
        a = self._auth(b"\x01" * 32)
        b = self._auth(b"\x02" * 32)
        cert = a.get_cert()
        assert not b.verify_remote_cert(
            cert, b.node_secret.public_key.ed25519)

    def test_expired_cert_rejected(self):
        a = self._auth(b"\x01" * 32, now=lambda: 1000)
        cert = a.get_cert()
        late = self._auth(b"\x02" * 32, now=lambda: 10**9)
        assert not late.verify_remote_cert(
            cert, a.node_secret.public_key.ed25519)

    def test_shared_keys_symmetric_and_direction_distinct(self):
        a = self._auth(b"\x01" * 32)
        b = self._auth(b"\x02" * 32)
        na, nb = b"\x0a" * 32, b"\x0b" * 32
        a_send, a_recv = a.shared_keys(b.auth_public, na, nb, True)
        b_send, b_recv = b.shared_keys(a.auth_public, nb, na, False)
        assert a_send == b_recv and a_recv == b_send
        assert a_send != a_recv


# ---------------------------------------------------------------------------
# full-node helpers

def _make_node(clock, secret, qset, seed):
    lm = LedgerManager(NID)
    lm.start_new_ledger()
    herder = Herder(clock, lm, secret, qset)
    overlay = OverlayManager(clock, herder, NID, secret, auth_seed=seed)
    return herder, overlay


def _crank(clock, n=50):
    for _ in range(n):
        clock.crank()


class TestLoopbackHandshake:
    def setup_method(self):
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        self.sk_a, self.sk_b = SecretKey(b"\x0a" * 32), SecretKey(b"\x0b" * 32)
        q = qset_of([self.sk_a.public_key.ed25519,
                     self.sk_b.public_key.ed25519], 2)
        self.ha, self.oa = _make_node(self.clock, self.sk_a, q, b"a" * 32)
        self.hb, self.ob = _make_node(self.clock, self.sk_b, q, b"b" * 32)

    def test_handshake_authenticates_both_sides(self):
        pa, pb = make_loopback_pair(self.oa, self.ob)
        _crank(self.clock)
        assert pa.is_authenticated() and pb.is_authenticated()
        assert pa.peer_id == self.sk_b.public_key.ed25519
        assert pb.peer_id == self.sk_a.public_key.ed25519
        assert self.oa.num_authenticated() == 1
        assert self.ob.num_authenticated() == 1

    def test_bad_cert_rejected(self):
        # B presents a cert signed by the wrong identity
        evil = PeerAuth(SecretKey(b"\x66" * 32), NID,
                        self.clock.system_now, auth_seed=b"evil" * 8)
        self.ob.peer_auth.node_secret = SecretKey(b"\x66" * 32)
        pa, pb = make_loopback_pair(self.oa, self.ob)
        _crank(self.clock)
        assert not pa.is_authenticated()
        assert pa.drop_reason is not None or pb.drop_reason is not None

    def test_wrong_network_dropped(self):
        self.ob.network_id = network_id("some other network")
        self.ob.peer_auth.network_id = self.ob.network_id
        pa, pb = make_loopback_pair(self.oa, self.ob)
        _crank(self.clock)
        assert not pa.is_authenticated() and not pb.is_authenticated()

    def test_tampered_mac_drops_peer(self):
        pa, pb = make_loopback_pair(self.oa, self.ob)
        _crank(self.clock)
        assert pa.is_authenticated()
        # hand-craft a message with a garbage MAC
        msg = X.StellarMessage.getPeers()
        am = X.AuthenticatedMessage.v0(X.AuthenticatedMessageV0(
            sequence=pb._recv_seq, message=msg,
            mac=X.HmacSha256Mac(mac=b"\xff" * 32)))
        pb.data_received(frame_encode(am.to_xdr()))
        assert pb.drop_reason == "bad MAC or sequence"

    def test_replayed_sequence_drops_peer(self):
        pa, pb = make_loopback_pair(self.oa, self.ob)
        _crank(self.clock)
        from stellar_core_tpu.overlay.peer_auth import mac_message
        msg = X.StellarMessage.getPeers()
        body = msg.to_xdr()
        seq = 0  # already consumed by AUTH
        am = X.AuthenticatedMessage.v0(X.AuthenticatedMessageV0(
            sequence=seq, message=msg,
            mac=X.HmacSha256Mac(mac=mac_message(pa._send_key, seq, body))))
        pb.data_received(frame_encode(am.to_xdr()))
        assert pb.drop_reason == "bad MAC or sequence"


class TestLoopbackConsensus:
    def test_two_validators_reach_externalize_over_overlay(self):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        sk_a, sk_b = SecretKey(b"\x0a" * 32), SecretKey(b"\x0b" * 32)
        q = qset_of([sk_a.public_key.ed25519, sk_b.public_key.ed25519], 2)
        ha, oa = _make_node(clock, sk_a, q, b"a" * 32)
        hb, ob = _make_node(clock, sk_b, q, b"b" * 32)
        make_loopback_pair(oa, ob)
        _crank(clock)
        ha.bootstrap()
        hb.bootstrap()
        ok = clock.crank_until(
            lambda: ha.lm.last_closed_ledger_seq >= 3
            and hb.lm.last_closed_ledger_seq >= 3, timeout=120)
        assert ok, (ha.lm.last_closed_ledger_seq,
                    hb.lm.last_closed_ledger_seq)
        assert ha.lm.lcl_hash == hb.lm.lcl_hash

    def test_transaction_floods_and_externalizes_everywhere(self):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        sk_a, sk_b = SecretKey(b"\x0a" * 32), SecretKey(b"\x0b" * 32)
        q = qset_of([sk_a.public_key.ed25519, sk_b.public_key.ed25519], 2)
        ha, oa = _make_node(clock, sk_a, q, b"a" * 32)
        hb, ob = _make_node(clock, sk_b, q, b"b" * 32)
        make_loopback_pair(oa, ob)
        _crank(clock)
        ha.bootstrap()
        hb.bootstrap()
        clock.crank_until(lambda: ha.lm.last_closed_ledger_seq >= 2,
                          timeout=60)
        # submit to A only; pull-mode flood must carry it to B's queue and
        # consensus must apply it on both
        root_sk = ha.lm.root_account_secret()
        e = ha.lm.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                root_sk.public_key.ed25519))).to_xdr())
        root = TestAccount(ha.lm, root_sk, e.data.value.seqNum)
        dest = SecretKey(b"\x77" * 32)
        frame = root.tx([create_account_op(
            X.AccountID.ed25519(dest.public_key.ed25519), 10**10)])
        assert ha.recv_transaction(frame).code == "pending"
        ha.tx_flood(frame)
        oa.flush_adverts()
        dest_key = X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                dest.public_key.ed25519))).to_xdr()
        ok = clock.crank_until(
            lambda: hb.lm.root.get_entry(dest_key) is not None
            and ha.lm.root.get_entry(dest_key) is not None, timeout=120)
        assert ok
        assert ha.lm.lcl_hash is not None

    def test_late_joiner_fetches_missing_txset(self):
        """C joins after consensus traffic exists; its pending envelopes
        must fetch tx sets / qsets via the overlay item fetcher."""
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        sks = [SecretKey(bytes([0x0a + i]) * 32) for i in range(3)]
        ids = [s.public_key.ed25519 for s in sks]
        q = qset_of(ids, 2)
        nodes = [_make_node(clock, s, q, bytes([0x61 + i]) * 32)
                 for i, s in enumerate(sks)]
        (ha, oa), (hb, ob), (hc, oc) = nodes
        make_loopback_pair(oa, ob)
        _crank(clock)
        ha.bootstrap()
        hb.bootstrap()
        clock.crank_until(lambda: ha.lm.last_closed_ledger_seq >= 2,
                          timeout=60)
        # now connect C to both; it must sync via SCP state + item fetch
        make_loopback_pair(oc, oa)
        make_loopback_pair(oc, ob)
        _crank(clock)
        hc.start()
        ok = clock.crank_until(
            lambda: hc.lm.last_closed_ledger_seq
            >= ha.lm.last_closed_ledger_seq - 1, timeout=180)
        assert ok, (hc.lm.last_closed_ledger_seq,
                    ha.lm.last_closed_ledger_seq)


class TestFlowControl:
    def test_flood_queue_respects_capacity_and_drains_on_send_more(self):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        sk_a, sk_b = SecretKey(b"\x0a" * 32), SecretKey(b"\x0b" * 32)
        q = qset_of([sk_a.public_key.ed25519, sk_b.public_key.ed25519], 2)
        ha, oa = _make_node(clock, sk_a, q, b"a" * 32)
        hb, ob = _make_node(clock, sk_b, q, b"b" * 32)
        pa, pb = make_loopback_pair(oa, ob)
        _crank(clock)
        assert pa.is_authenticated()
        assert pa._outbound_capacity > 0
        # exhaust A's grant without letting B process (black-hole outbound)
        pa._outbound_capacity = 2
        pa._outbound_capacity_bytes = 10**9
        pa.drop_outbound = True
        env = X.SCPEnvelope(
            statement=X.SCPStatement(
                nodeID=X.AccountID.ed25519(sk_a.public_key.ed25519),
                slotIndex=99,
                pledges=X.SCPStatementPledges.nominate(X.SCPNomination(
                    quorumSetHash=b"\x02" * 32, votes=[], accepted=[]))),
            signature=b"\x03" * 64)
        for _ in range(5):
            pa.send_message(X.StellarMessage.envelope(env))
        assert pa.flood_queue_len == 3      # 2 sent, 3 queued
        # a SEND_MORE grant from B drains the queue
        pa.drop_outbound = False
        from stellar_core_tpu.overlay.peer_auth import mac_message
        grant = X.StellarMessage.sendMoreMessage(X.SendMore(numMessages=10))
        body = grant.to_xdr()
        am = X.AuthenticatedMessage.v0(X.AuthenticatedMessageV0(
            sequence=pa._recv_seq, message=grant,
            mac=X.HmacSha256Mac(
                mac=mac_message(pa._recv_key, pa._recv_seq, body))))
        pa.data_received(frame_encode(am.to_xdr()))
        assert pa.flood_queue_len == 0


# ---------------------------------------------------------------------------
# real TCP sockets

class TestOverTCP:
    def test_three_node_network_closes_ledgers_over_tcp(self, monkeypatch):
        """The VERDICT 'done' bar: real processes' worth of nodes (in one
        process, real sockets) reach externalize over TCP."""
        from stellar_core_tpu.herder import herder as herder_mod
        monkeypatch.setattr(herder_mod, "EXP_LEDGER_TIMESPAN_SECONDS", 0.3)
        clock = VirtualClock(ClockMode.REAL_TIME)
        sks = [SecretKey(bytes([0x0a + i]) * 32) for i in range(3)]
        ids = [s.public_key.ed25519 for s in sks]
        q = qset_of(ids, 2)
        nodes = []
        transports = []
        closed = [{} for _ in range(3)]
        for i, s in enumerate(sks):
            h, o = _make_node(clock, s, q, bytes([0x41 + i]) * 32)
            h.ledger_closed_hook = (
                lambda arts, d=closed[i]: d.__setitem__(
                    arts.header_entry.header.ledgerSeq,
                    arts.header_entry.hash))
            t = TCPTransport(o, listen_port=0)
            nodes.append((h, o))
            transports.append(t)
        try:
            # full mesh dialing
            for i in range(3):
                for j in range(i + 1, 3):
                    transports[i].connect("127.0.0.1",
                                          nodes[j][1].listening_port)
            ok = clock.crank_until(
                lambda: all(o.num_authenticated() >= 2 for _, o in nodes),
                timeout=10)
            assert ok, [o.num_authenticated() for _, o in nodes]
            for h, _ in nodes:
                h.bootstrap()
            ok = clock.crank_until(
                lambda: all(h.lm.last_closed_ledger_seq >= 3
                            for h, _ in nodes), timeout=30)
            assert ok, [h.lm.last_closed_ledger_seq for h, _ in nodes]
            # no fork: every commonly-closed ledger hash agrees
            for seq in (2, 3):
                hashes = {d[seq] for d in closed if seq in d}
                assert len(hashes) == 1, f"fork at ledger {seq}"
        finally:
            for t in transports:
                t.close()


class TestTCPTransportEdgeCases:
    """The three failure shapes LoopbackPeer structurally cannot exercise
    (ISSUE 11 satellite): partial-frame reassembly across READ_CHUNK
    boundaries, a half-open peer (remote closes with writes still
    buffered), and the MAX_WRITE_BUFFER overflow drop path."""

    def _tcp_pair(self, clock_a=None, clock_b=None):
        """Two nodes with real sockets; separate clocks let a test crank
        one side only (a peer that stops reading)."""
        clock_a = clock_a or VirtualClock(ClockMode.REAL_TIME)
        clock_b = clock_b or clock_a
        sk_a, sk_b = SecretKey(b"\x91" * 32), SecretKey(b"\x92" * 32)
        q = qset_of([sk_a.public_key.ed25519, sk_b.public_key.ed25519], 2)

        def mk(clock, sk, seed):
            lm = LedgerManager(NID)
            lm.start_new_ledger()
            h = Herder(clock, lm, sk, q)
            o = OverlayManager(clock, h, NID, sk, auth_seed=seed)
            return h, o

        ha, oa = mk(clock_a, sk_a, b"A" * 32)
        hb, ob = mk(clock_b, sk_b, b"B" * 32)
        ta = TCPTransport(oa, listen_port=None)
        tb = TCPTransport(ob, listen_port=0)
        pa = ta.connect("127.0.0.1", ob.listening_port)

        import time as _t
        deadline = _t.time() + 10
        while _t.time() < deadline:
            progressed = clock_a.crank()
            if clock_b is not clock_a:
                progressed += clock_b.crank()
            if pa.is_authenticated() and ob.num_authenticated() == 1:
                break
            if not progressed:
                _t.sleep(0.001)
        assert pa.is_authenticated()
        pb = next(iter(tb.peers.values()))
        assert pb.is_authenticated()
        return (clock_a, clock_b), (ta, tb), (pa, pb), (oa, ob)

    def test_partial_frame_reassembly_across_read_chunk(self):
        """One authenticated frame larger than READ_CHUNK arrives in
        multiple recv() slices; the decoder must reassemble it into
        exactly one intact message."""
        from stellar_core_tpu.overlay import tcp as tcp_mod
        (ca, cb), (ta, tb), (pa, pb), (oa, ob) = self._tcp_pair()
        try:
            # ~50 txs x 100 ops ≈ 287KB > READ_CHUNK (256KB), single frame
            txset = X.TransactionSet(previousLedgerHash=b"\x00" * 32,
                                     txs=[_large_envelope()] * 50)
            msg = X.StellarMessage.txSet(txset)
            assert len(msg.to_xdr()) > tcp_mod.READ_CHUNK
            got = []
            orig = ob._message_received
            ob._message_received = \
                lambda peer, m, **kw: (got.append(m), orig(peer, m, **kw))
            pa.send_message(msg)
            ok = ca.crank_until(
                lambda: any(m.switch == X.MessageType.TX_SET for m in got),
                timeout=10)
            assert ok, "large frame never reassembled"
            big = [m for m in got if m.switch == X.MessageType.TX_SET][0]
            assert len(big.value.txs) == 50
            assert big.value.to_xdr() == txset.to_xdr()
            assert pb.is_authenticated()   # stream intact, MAC chain alive
        finally:
            ta.close()
            tb.close()

    def test_half_open_peer_with_buffered_writes_drops_cleanly(self):
        """Remote dies (socket closed, never read) while our side still
        has frames buffered: the next flush must surface the socket error
        as a clean drop, never an unhandled exception."""
        (ca, cb), (ta, tb), (pa, pb), (oa, ob) = self._tcp_pair(
            clock_a=VirtualClock(ClockMode.REAL_TIME),
            clock_b=VirtualClock(ClockMode.REAL_TIME))
        try:
            # shrink A's kernel send buffer so writes actually buffer
            import socket as pysock
            pa.sock.setsockopt(pysock.SOL_SOCKET, pysock.SO_SNDBUF, 8192)
            big = X.StellarMessage.txSet(X.TransactionSet(
                previousLedgerHash=b"\x01" * 32,
                txs=[_large_envelope()] * 8))
            # B stops pumping (its clock is never cranked again): B's
            # receive buffer fills, then A's kernel send buffer, then
            # A's user-space write buffer
            for _ in range(60):
                pa.send_message(big)
                if pa._write_buf:
                    break
            assert pa._write_buf, "writes never buffered"
            # remote closes with data in flight -> RST on next send
            pb.sock.close()
            for _ in range(400):
                ca.crank()
                if pa.state == Peer.CLOSING:
                    break
            assert pa.state == Peer.CLOSING
            assert pa.drop_reason is not None
            assert ("error" in pa.drop_reason
                    or "closed" in pa.drop_reason), pa.drop_reason
            # the transport forgot the peer and survives further pumps
            assert pa.sock is None
            ca.crank()
        finally:
            ta.close()
            tb.close()

    def test_max_write_buffer_overflow_drops_peer(self, monkeypatch):
        """A peer that stops reading while we keep sending must be
        dropped at the MAX_WRITE_BUFFER bound — bounded memory per
        connection, not an OOM (reference: TCPPeer write-queue limits)."""
        from stellar_core_tpu.overlay import tcp as tcp_mod
        clock_a = VirtualClock(ClockMode.REAL_TIME)
        clock_b = VirtualClock(ClockMode.REAL_TIME)   # never cranked after auth
        (ca, cb), (ta, tb), (pa, pb), (oa, ob) = self._tcp_pair(
            clock_a=clock_a, clock_b=clock_b)
        try:
            monkeypatch.setattr(tcp_mod, "MAX_WRITE_BUFFER", 128 * 1024)
            import socket as pysock
            pa.sock.setsockopt(pysock.SOL_SOCKET, pysock.SO_SNDBUF, 8192)
            payload = X.StellarMessage.txSet(X.TransactionSet(
                previousLedgerHash=b"\x02" * 32,
                txs=[_large_envelope()] * 2))
            blob_len = len(payload.to_xdr())
            # B never cranks -> never reads -> kernel buffers fill ->
            # A's user-space buffer grows to the (patched) cap
            sent = 0
            while pa.state != Peer.CLOSING and sent < 2000:
                pa.send_message(payload)
                sent += 1
            assert pa.state == Peer.CLOSING, \
                f"no overflow after {sent} sends of {blob_len}B"
            assert pa.drop_reason == "write buffer overflow"
            # bounded: the buffer never grew far past the cap
            assert len(pa._write_buf) <= 128 * 1024 + 2 * (blob_len + 64)
        finally:
            ta.close()
            tb.close()

    def test_synchronous_connect_failure_is_a_clean_drop(self):
        """A dial that fails synchronously (unroutable address) must
        record a normal drop, not crash the crank loop."""
        clock = VirtualClock(ClockMode.REAL_TIME)
        sk = SecretKey(b"\x94" * 32)
        q = qset_of([sk.public_key.ed25519], 1)
        lm = LedgerManager(NID)
        lm.start_new_ledger()
        h = Herder(clock, lm, sk, q)
        o = OverlayManager(clock, h, NID, sk, auth_seed=b"Z" * 32)
        t = TCPTransport(o, listen_port=None)
        try:
            # unparseable address: resolution fails synchronously
            peer = t.connect("256.256.256.256", 1)
            for _ in range(100):
                clock.crank()
                if peer.state == Peer.CLOSING:
                    break
            assert peer.state == Peer.CLOSING
            assert "connect failed" in (peer.drop_reason or "")
        finally:
            t.close()


class TestPeerDiscovery:
    def test_peers_gossip_reaches_new_node(self, tmp_path):
        """C only knows A; A knows B: PEERS gossip must teach C about B
        (reference: GET_PEERS/PEERS + PeerManager address book)."""
        from stellar_core_tpu.database import Database
        from stellar_core_tpu.overlay.peer_manager import PeerManager

        clock = VirtualClock(ClockMode.REAL_TIME)
        sks = [SecretKey(bytes([0x0a + i]) * 32) for i in range(3)]
        ids = [s.public_key.ed25519 for s in sks]
        q = qset_of(ids, 2)
        nodes, transports = [], []
        for i, s in enumerate(sks):
            h, o = _make_node(clock, s, q, bytes([0x71 + i]) * 32)
            t = TCPTransport(o, listen_port=0)
            nodes.append((h, o))
            transports.append(t)
        (ha, oa), (hb, ob), (hc, oc) = nodes
        try:
            # A <-> B connected; then C dials only A
            transports[0].connect("127.0.0.1", ob.listening_port)
            ok = clock.crank_until(
                lambda: oa.num_authenticated() >= 1
                and ob.num_authenticated() >= 1, timeout=10)
            assert ok
            transports[2].connect("127.0.0.1", oa.listening_port)
            # C learns B's address via the PEERS exchange
            ok = clock.crank_until(
                lambda: any(port == ob.listening_port
                            for _, port in
                            oc.peer_manager.dial_candidates(50)), timeout=10)
            assert ok, [r for r in oc.peer_manager._records]
        finally:
            for t in transports:
                t.close()

    def test_peer_manager_backoff_and_persistence(self, tmp_path):
        from stellar_core_tpu.database import Database
        from stellar_core_tpu.overlay.peer_manager import PeerManager

        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        db = Database(str(tmp_path / "p.db"))
        pm = PeerManager(clock, db)
        pm.add_address("10.0.0.1", 11625)
        pm.add_address("10.0.0.2", 11625)
        assert len(pm.dial_candidates(10)) == 2
        pm.record_failure("10.0.0.1", 11625)
        # failed address backs off
        assert pm.dial_candidates(10) == [("10.0.0.2", 11625)]
        clock._virtual_now += 3600
        assert len(pm.dial_candidates(10)) == 2
        # persisted across restart
        pm2 = PeerManager(clock, Database(db.path))
        assert pm2.size == 2
        # repeated failures forget the address
        for _ in range(20):
            pm.record_failure("10.0.0.1", 11625)
        assert pm.size == 1


class TestSurvey:
    """Reference: src/overlay/test/SurveyManagerTests.cpp — time-sliced
    survey over a 3-node chain: surveyor A, relay B, surveyed C."""

    def _three_chain(self):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        sks = [SecretKey(bytes([0x30 + i]) * 32) for i in range(3)]
        q = qset_of([s.public_key.ed25519 for s in sks], 2)
        nodes = [_make_node(clock, s, q, bytes([0x40 + i]) * 32)
                 for i, s in enumerate(sks)]
        # chain A - B - C (A and C are not neighbours)
        make_loopback_pair(nodes[0][1], nodes[1][1])
        make_loopback_pair(nodes[1][1], nodes[2][1])
        _crank(clock)
        return clock, sks, nodes

    def test_survey_roundtrip_through_relay(self):
        clock, sks, nodes = self._three_chain()
        oa, oc = nodes[0][1], nodes[2][1]
        nonce = oa.survey.start_survey(nonce=7)
        _crank(clock)
        assert oc.survey.collecting is not None
        assert oc.survey.collecting.nonce == nonce
        oa.survey.send_request(sks[2].public_key.ed25519)
        _crank(clock)
        res = oa.survey.results()
        key = sks[2].public_key.ed25519.hex()
        assert key in res["topology"], res
        body = res["topology"][key]
        # C has one authenticated peer (B)
        total = body["nodeData"]["totalInbound"] \
            + body["nodeData"]["totalOutbound"]
        assert total == 1
        oa.survey.stop_survey()
        _crank(clock)
        assert oc.survey.collecting is None

    def test_request_outside_collecting_phase_ignored(self):
        clock, sks, nodes = self._three_chain()
        oa, oc = nodes[0][1], nodes[2][1]
        # no start_survey: requests must be dropped, nothing recorded
        oa.survey._nonce = 99
        from stellar_core_tpu.crypto import box as cbox
        oa.survey._enc_pk, oa.survey._enc_sk = cbox.keypair(b"k" * 32)
        oa.survey.send_request(sks[2].public_key.ed25519)
        _crank(clock)
        assert oa.survey.results()["topology"] == {}

    def test_relay_forwards_request_when_not_collecting(self):
        """A relay that missed/expired its own collecting phase must still
        forward requests whose nonce belongs to the known active survey
        (reference: relay keyed on active-survey nonce, not local state)."""
        clock, sks, nodes = self._three_chain()
        oa, ob, oc = nodes[0][1], nodes[1][1], nodes[2][1]
        nonce = oa.survey.start_survey(nonce=11)
        _crank(clock)
        # B drops its local collecting state (e.g. expiry); the nonce stays
        # known, so A's request still reaches C through B
        ob.survey.collecting = None
        oa.survey.send_request(sks[2].public_key.ed25519)
        _crank(clock)
        assert sks[2].public_key.ed25519.hex() in \
            oa.survey.results()["topology"]

    def test_nonce_rider_and_forged_stop_rejected(self):
        """An unprivileged peer must not be able to ride a live survey
        nonce (relay amplification) or kill relaying with a self-signed
        stop — both are bound to the starting surveyor."""
        clock, sks, nodes = self._three_chain()
        oa, ob = nodes[0][1], nodes[1][1]
        nonce = oa.survey.start_survey(nonce=12)
        _crank(clock)
        ob.survey.collecting = None   # relay-only state on B
        from stellar_core_tpu import xdr as X
        evil = SecretKey(b"\x66" * 32)
        sm = ob.survey
        # evil request riding the live nonce: signature verifies (it is
        # self-signed) but the surveyor does not match the nonce's owner
        req = X.TimeSlicedSurveyRequestMessage(
            request=X.SurveyRequestMessage(
                surveyorPeerID=X.NodeID.ed25519(evil.public_key.ed25519),
                surveyedPeerID=X.NodeID.ed25519(b"\x07" * 32),
                ledgerNum=1,
                encryptionKey=X.Curve25519Public(key=b"\x01" * 32)),
            nonce=nonce)
        sr = X.SignedTimeSlicedSurveyRequestMessage(
            requestSignature=evil.sign(sm.TAG_REQUEST + req.to_xdr()),
            request=req)
        assert sm.recv_request(None, sr) is False
        # evil stop: must neither clear the known nonce nor be relayed
        stop = X.TimeSlicedSurveyStopCollectingMessage(
            surveyorID=X.NodeID.ed25519(evil.public_key.ed25519),
            nonce=nonce, ledgerNum=1)
        st = X.SignedTimeSlicedSurveyStopCollectingMessage(
            signature=evil.sign(sm.TAG_STOP + stop.to_xdr()),
            stopCollecting=stop)
        assert sm.recv_stop_collecting(None, st) is False
        assert nonce in sm._known_nonces

    def test_nonce_memory_bounded_and_first_writer_wins(self):
        """Relay nonce memory is attacker-writable: it must be hard-capped,
        expire on OUR ledger clock (not the message's claimed ledgerNum),
        and never rebind a live nonce to a different surveyor."""
        from stellar_core_tpu.overlay.survey import MAX_KNOWN_NONCES
        clock, sks, nodes = self._three_chain()
        ob = nodes[1][1]
        sm = ob.survey
        surveyor_sk = sks[0]

        def start(nonce, ledger_num, sk=surveyor_sk):
            msg = X.TimeSlicedSurveyStartCollectingMessage(
                surveyorID=X.NodeID.ed25519(sk.public_key.ed25519),
                nonce=nonce, ledgerNum=ledger_num)
            return X.SignedTimeSlicedSurveyStartCollectingMessage(
                signature=sk.sign(sm.TAG_START + msg.to_xdr()),
                startCollecting=msg)

        # claimed far-future ledgerNum must not pin entries: expiry uses
        # the local ledger
        sm.recv_start_collecting(None, start(1, 2**31 - 1))
        assert sm._known_nonces[1][1] <= sm._ledger_num()
        # a reused live nonce keeps its first surveyor binding
        sm.recv_start_collecting(None, start(1, 5, sk=sks[2]))
        assert sm._known_nonces[1][0] == surveyor_sk.public_key.ed25519
        # the memory is hard-capped
        for n in range(2, MAX_KNOWN_NONCES + 50):
            sm.recv_start_collecting(None, start(n, 5))
        assert len(sm._known_nonces) <= MAX_KNOWN_NONCES

    def test_forged_start_collecting_rejected(self):
        clock, sks, nodes = self._three_chain()
        oc = nodes[2][1]
        from stellar_core_tpu import xdr as X
        msg = X.TimeSlicedSurveyStartCollectingMessage(
            surveyorID=X.NodeID.ed25519(sks[0].public_key.ed25519),
            nonce=1, ledgerNum=1)
        forged = X.SignedTimeSlicedSurveyStartCollectingMessage(
            signature=b"\x00" * 64, startCollecting=msg)
        assert oc.survey.recv_start_collecting(None, forged) is False
        assert oc.survey.collecting is None

    def test_unauthorized_surveyor_rejected(self):
        """Only transitive-quorum members may survey (reference:
        SurveyManager surveyor permission check)."""
        clock, sks, nodes = self._three_chain()
        oc = nodes[2][1]
        from stellar_core_tpu import xdr as X
        from stellar_core_tpu.crypto.keys import SecretKey
        stranger = SecretKey(b"\x7e" * 32)
        msg = X.TimeSlicedSurveyStartCollectingMessage(
            surveyorID=X.NodeID.ed25519(stranger.public_key.ed25519),
            nonce=5, ledgerNum=1)
        signed = X.SignedTimeSlicedSurveyStartCollectingMessage(
            signature=stranger.sign(oc.survey.TAG_START + msg.to_xdr()),
            startCollecting=msg)
        assert oc.survey.recv_start_collecting(None, signed) is False
        assert oc.survey.collecting is None

    def test_second_start_does_not_clobber_live_survey(self):
        clock, sks, nodes = self._three_chain()
        oa, ob, oc = (n[1] for n in nodes)
        oa.survey.start_survey(nonce=1)
        _crank(clock)
        assert oc.survey.collecting.nonce == 1
        # B (also in quorum) tries to start its own survey: C must keep
        # the live phase
        ob.survey.start_survey(nonce=2)
        _crank(clock)
        assert oc.survey.collecting.nonce == 1


class TestBanManager:
    def test_ban_drops_and_persists(self, tmp_path):
        from stellar_core_tpu.database import Database
        from stellar_core_tpu.overlay.ban import BanManager
        db = Database(str(tmp_path / "ban.db"))
        bm = BanManager(db)
        nid = b"\x07" * 32
        bm.ban_node(nid)
        assert bm.is_banned(nid)
        bm2 = BanManager(Database(db.path))  # fresh load from disk
        assert bm2.is_banned(nid)
        bm2.unban_node(nid)
        assert not bm2.is_banned(nid)
        assert BanManager(Database(db.path)).banned_nodes() == []

    def test_banned_peer_cannot_authenticate(self):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        sk_a, sk_b = SecretKey(b"\x51" * 32), SecretKey(b"\x52" * 32)
        q = qset_of([sk_a.public_key.ed25519, sk_b.public_key.ed25519], 2)
        ha, oa = _make_node(clock, sk_a, q, b"x" * 32)
        hb, ob = _make_node(clock, sk_b, q, b"y" * 32)
        oa.ban_manager.ban_node(sk_b.public_key.ed25519)
        pa, pb = make_loopback_pair(oa, ob)
        _crank(clock)
        assert oa.num_authenticated() == 0


class TestLoopbackFaultInjection:
    """Reference: LoopbackPeer damage/drop/reorder knobs — the overlay must
    fail-stop (drop the peer) on damaged frames, never crash."""

    def _pair(self):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        sk_a, sk_b = SecretKey(b"\x81" * 32), SecretKey(b"\x82" * 32)
        q = qset_of([sk_a.public_key.ed25519, sk_b.public_key.ed25519], 2)
        ha, oa = _make_node(clock, sk_a, q, b"p" * 32)
        hb, ob = _make_node(clock, sk_b, q, b"q" * 32)
        pa, pb = make_loopback_pair(oa, ob)
        _crank(clock)
        assert pa.is_authenticated() and pb.is_authenticated()
        return clock, pa, pb

    def test_damaged_frame_drops_peer_not_process(self):
        clock, pa, pb = self._pair()
        pa.damage_probability = 1.0
        from stellar_core_tpu import xdr as X
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(7))
        _crank(clock)
        # HMAC over the damaged frame fails -> peer dropped, no exception
        assert pb.state == pb.CLOSING or pa.state == pa.CLOSING

    def test_dropped_frames_are_silent(self):
        clock, pa, pb = self._pair()
        pa.drop_probability = 1.0
        from stellar_core_tpu import xdr as X
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(7))
        _crank(clock)
        assert pa.is_authenticated() and pb.is_authenticated()

    def test_reordered_frames_break_auth_sequence(self):
        """Authenticated streams are sequence-numbered: reordering must be
        detected (reference: per-message sequence in the HMAC).  Batching
        is disabled on the sender — coalesced, these two messages would
        legally share one frame (intra-batch order is covered by the
        batch's single MAC; see TestBatchedTransport)."""
        clock, pa, pb = self._pair()
        pa.batching_enabled = False
        pa.reorder_probability = 1.0
        from stellar_core_tpu import xdr as X
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(1))
        pa.reorder_probability = 0.0
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(2))
        _crank(clock)
        assert pb.state == pb.CLOSING or pa.state == pa.CLOSING

    def test_reorder_held_frame_not_lost_when_stream_quiesces(self):
        """A held-back frame with no successor must still arrive (reorder
        must not degrade into drop)."""
        clock, pa, pb = self._pair()
        pa.reorder_probability = 1.0
        from stellar_core_tpu import xdr as X
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(9))
        pa.reorder_probability = 0.0
        _crank(clock)
        # the single (held) frame was flushed by the backstop and, being
        # alone, arrives in order: connection stays healthy
        assert pa.is_authenticated() and pb.is_authenticated()


class TestItemFetcherRetry:
    """A fetch request or reply frame lost in flight (lossy link, peer
    severed mid-fetch) must not wedge the tracker until an unrelated peer
    authenticates: the retry timer re-asks, a fully-exhausted round clears
    the asked set, and RETRY_LIMIT rounds drop a network-wide-dead hash."""

    def _fetcher(self, peers):
        from stellar_core_tpu.overlay.flood import ItemFetcher
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        asked = []
        f = ItemFetcher(lambda p, t, h: asked.append(p), clock=clock,
                        peers_fn=lambda: list(peers))
        return clock, f, asked

    def test_lost_reply_is_retried_on_timer(self):
        peers = ["peer-a", "peer-b"]
        clock, f, asked = self._fetcher(peers)
        f.fetch("txset", b"h" * 32, list(peers))
        assert asked == ["peer-a"]
        # replies never arrive; two retry rounds re-ask the other peer,
        # then (round exhausted, asked set cleared) the first one again
        clock.crank_for(2 * f.RETRY_PERIOD_S + 0.1)
        assert asked[:3] == ["peer-a", "peer-b", "peer-a"]
        clock.stop()

    def test_answer_cancels_retry(self):
        peers = ["peer-a", "peer-b"]
        clock, f, asked = self._fetcher(peers)
        f.fetch("txset", b"h" * 32, list(peers))
        f.stop_fetch(b"h" * 32)
        clock.crank_for(5 * f.RETRY_PERIOD_S)
        assert asked == ["peer-a"] and f.wanted() == []
        clock.stop()

    def test_retry_limit_drops_dead_hash(self):
        clock, f, asked = self._fetcher([])
        f.fetch("qset", b"g" * 32, [])
        clock.crank_for((f.RETRY_LIMIT + 2) * f.RETRY_PERIOD_S)
        assert f.wanted() == []
        clock.stop()


# ---------------------------------------------------------------------------
# batched authenticated transport

class TestBatchedTransport:
    """BATCHED_AUTH frames: one sequence number + one MAC authenticate a
    packed run of message bodies.  Covers the splice/codec byte identity,
    per-link negotiation, coalescing + the single-message floor, MAC/seq
    fail-stop with NO partial dispatch, and per-contained-message flow
    control."""

    def _pair(self, batch_a=True, batch_b=True):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        sk_a, sk_b = SecretKey(b"\x91" * 32), SecretKey(b"\x92" * 32)
        q = qset_of([sk_a.public_key.ed25519, sk_b.public_key.ed25519], 2)
        ha, oa = _make_node(clock, sk_a, q, b"r" * 32)
        hb, ob = _make_node(clock, sk_b, q, b"s" * 32)
        oa.batching, ob.batching = batch_a, batch_b
        pa, pb = make_loopback_pair(oa, ob)
        _crank(clock)
        assert pa.is_authenticated() and pb.is_authenticated()
        return clock, pa, pb

    @staticmethod
    def _capture_frames(peer):
        sent = []
        orig = peer._write_frame

        def spy(frame):
            sent.append(frame)
            orig(frame)
        peer._write_frame = spy
        return sent

    @staticmethod
    def _capture_received(peer):
        got = []
        orig = peer.overlay._message_received

        def spy(p, msg, body=None, **kw):
            if p is peer:
                got.append(msg.switch)
            return orig(p, msg, body=body, **kw)
        peer.overlay._message_received = spy
        return got

    @staticmethod
    def _batch_frame(key, seq, bodies, mac=None, count=None,
                     chop=0):
        """Hand-craft a BATCHED_AUTH frame the way the sender splices it;
        `count`/`mac`/`chop` let tests lie about the run."""
        import struct
        from stellar_core_tpu.overlay.peer_auth import mac_message
        payload = struct.pack(
            ">I", len(bodies) if count is None else count)
        for b in bodies:
            payload += struct.pack(">I", len(b)) + b
        if chop:
            payload = payload[:-chop]
        if mac is None:
            mac = mac_message(key, seq, payload)
        return frame_encode(b"\x00\x00\x00\x01"
                            + struct.pack(">Q", seq) + payload + mac)

    def test_batch_splice_matches_codec_path(self):
        """The spliced batch frame must be byte-identical to encoding a
        BatchedAuthenticatedMessage through the codec (XDR bodies are
        4-aligned, so the var-opaque padding is empty)."""
        import struct
        bodies = [X.StellarMessage.getPeers().to_xdr(),
                  X.StellarMessage.getSCPLedgerSeq(5).to_xdr()]
        mac = b"\xab" * 32
        for seq in (0, 7, 2**40):
            am = X.AuthenticatedMessage.batch(X.BatchedAuthenticatedMessage(
                sequence=seq, messages=bodies,
                mac=X.HmacSha256Mac(mac=mac)))
            spliced = (b"\x00\x00\x00\x01" + struct.pack(">Q", seq)
                       + struct.pack(">I", len(bodies))
                       + b"".join(struct.pack(">I", len(b)) + b
                                  for b in bodies)
                       + mac)
            assert am.to_xdr() == spliced

    def test_coalescing_one_frame_per_crank_edge(self):
        clock, pa, pb = self._pair()
        sent = self._capture_frames(pa)
        got = self._capture_received(pb)
        for i in range(3):
            pa.send_message(X.StellarMessage.getSCPLedgerSeq(i + 1))
        assert sent == []            # run pending until the crank edge
        _crank(clock, 2)
        batch = [f for f in sent if f[4:8] == b"\x00\x00\x00\x01"]
        assert len(batch) == 1       # ONE arm-1 frame carried all three
        assert got.count(X.MessageType.GET_SCP_STATE) == 3
        assert pa.is_authenticated() and pb.is_authenticated()

    def test_single_message_floor_emits_plain_v0(self):
        """A run of one goes out as a classic per-message frame — the
        quiet path has zero wire or latency delta vs an unbatched link."""
        clock, pa, pb = self._pair()
        sent = self._capture_frames(pa)
        got = self._capture_received(pb)
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(9))
        _crank(clock, 2)
        assert len(sent) == 1
        assert sent[0][4:8] == b"\x00\x00\x00\x00"   # v0 arm, not batch
        assert got.count(X.MessageType.GET_SCP_STATE) == 1

    def test_unbatched_peer_negotiates_plain_frames(self):
        """flags=0 on either side keeps today's per-message wire format
        verbatim in BOTH directions."""
        clock, pa, pb = self._pair(batch_a=True, batch_b=False)
        assert not pa._remote_batch          # B never advertised
        sent_a = self._capture_frames(pa)
        sent_b = self._capture_frames(pb)
        got_b = self._capture_received(pb)
        got_a = self._capture_received(pa)
        for i in range(3):
            pa.send_message(X.StellarMessage.getSCPLedgerSeq(i + 1))
            pb.send_message(X.StellarMessage.getSCPLedgerSeq(i + 10))
        _crank(clock, 2)
        assert all(f[4:8] == b"\x00\x00\x00\x00" for f in sent_a)
        assert all(f[4:8] == b"\x00\x00\x00\x00" for f in sent_b)
        assert got_b.count(X.MessageType.GET_SCP_STATE) == 3
        assert got_a.count(X.MessageType.GET_SCP_STATE) == 3

    def test_unnegotiated_batch_frame_dropped(self):
        """A batch frame on a link where we never offered the flag is a
        protocol violation — fail-stop before touching the payload."""
        clock, pa, pb = self._pair(batch_a=False, batch_b=True)
        frame = self._batch_frame(
            pb._send_key, pa._recv_seq,
            [X.StellarMessage.getPeers().to_xdr()])
        pa.data_received(frame)
        assert pa.drop_reason == "unnegotiated batch frame"

    def test_tampered_byte_mid_batch_no_partial_dispatch(self):
        clock, pa, pb = self._pair()
        got = self._capture_received(pb)
        bodies = [X.StellarMessage.getSCPLedgerSeq(1).to_xdr(),
                  X.StellarMessage.getSCPLedgerSeq(2).to_xdr()]
        frame = bytearray(self._batch_frame(
            pa._send_key, pb._recv_seq, bodies))
        frame[20] ^= 0x01            # flip a byte inside the first body
        pb.data_received(bytes(frame))
        assert pb.drop_reason == "bad MAC or sequence"
        assert got == []             # nothing dispatched, not even msg 1

    def test_truncated_trailing_body_fail_stop(self):
        """count says 2, run carries 1 — even with a valid MAC over the
        truncated payload the framing check fail-stops with zero
        dispatch."""
        clock, pa, pb = self._pair()
        got = self._capture_received(pb)
        frame = self._batch_frame(
            pa._send_key, pb._recv_seq,
            [X.StellarMessage.getSCPLedgerSeq(1).to_xdr()], count=2)
        pb.data_received(frame)
        assert pb.drop_reason == "bad batch framing"
        assert got == []

    def test_truncated_mid_body_fails_mac(self):
        """Truncation in transit (MAC computed over the full run) is a
        MAC failure, like any damaged frame."""
        clock, pa, pb = self._pair()
        got = self._capture_received(pb)
        import struct
        from stellar_core_tpu.overlay.peer_auth import mac_message
        bodies = [X.StellarMessage.getSCPLedgerSeq(1).to_xdr(),
                  X.StellarMessage.getSCPLedgerSeq(2).to_xdr()]
        payload = struct.pack(">I", 2) + b"".join(
            struct.pack(">I", len(b)) + b for b in bodies)
        mac = mac_message(pa._send_key, pb._recv_seq, payload)
        frame = frame_encode(b"\x00\x00\x00\x01"
                             + struct.pack(">Q", pb._recv_seq)
                             + payload[:-8] + mac)
        pb.data_received(frame)
        assert pb.drop_reason == "bad MAC or sequence"
        assert got == []

    def test_whole_batch_replay_drops_peer(self):
        clock, pa, pb = self._pair()
        got = self._capture_received(pb)
        frame = self._batch_frame(
            pa._send_key, pb._recv_seq,
            [X.StellarMessage.getSCPLedgerSeq(1).to_xdr(),
             X.StellarMessage.getSCPLedgerSeq(2).to_xdr()])
        pb.data_received(frame)
        assert pb.drop_reason is None
        assert got.count(X.MessageType.GET_SCP_STATE) == 2
        pb.data_received(frame)      # replay the whole batch
        assert pb.drop_reason == "bad MAC or sequence"
        assert got.count(X.MessageType.GET_SCP_STATE) == 2

    def test_forbidden_types_inside_batch_rejected(self):
        """Handshake/teardown messages never ride inside a batch; every
        body is decoded before any is dispatched, so the legal first
        message must NOT be delivered either."""
        clock, pa, pb = self._pair()
        got = self._capture_received(pb)
        auth_body = X.StellarMessage.auth(X.Auth(flags=0)).to_xdr()
        frame = self._batch_frame(
            pa._send_key, pb._recv_seq,
            [X.StellarMessage.getSCPLedgerSeq(1).to_xdr(), auth_body])
        pb.data_received(frame)
        assert pb.drop_reason == "bad batch framing"
        assert got == []

    def _envelope(self, sk, slot):
        return X.SCPEnvelope(
            statement=X.SCPStatement(
                nodeID=X.AccountID.ed25519(sk.public_key.ed25519),
                slotIndex=slot,
                pledges=X.SCPStatementPledges.nominate(X.SCPNomination(
                    quorumSetHash=b"\x02" * 32, votes=[], accepted=[]))),
            signature=b"\x03" * 64)

    def test_duplicate_envelope_fast_drop_skips_decode(self, monkeypatch):
        """A flood duplicate arriving in a batch is recognised by its raw
        body hash BEFORE XDR decode (the dedup key is sha256 of exactly
        those bytes): no re-decode, no dispatch — but flow-control
        capacity is still earned per contained message and the sender is
        noted on the flood record so broadcast never echoes back."""
        from stellar_core_tpu.crypto.sha import sha256
        clock, pa, pb = self._pair()
        sk_a = SecretKey(b"\x91" * 32)
        msg = X.StellarMessage.envelope(self._envelope(sk_a, 1))
        h = sha256(msg.to_xdr())
        pa.send_message(msg)
        _crank(clock, 2)
        ob = pb.overlay
        assert ob.floodgate.seen(h)           # first copy recorded
        dedup0 = ob.stats["deduped"]
        earned0 = pb._processed_since_grant
        got = self._capture_received(pb)
        sent = self._capture_frames(pa)
        decoded = []
        orig = X.StellarMessage.from_xdr
        monkeypatch.setattr(
            X.StellarMessage, "from_xdr",
            staticmethod(lambda b: (decoded.append(sha256(b)), orig(b))[1]))
        fresh = X.StellarMessage.envelope(self._envelope(sk_a, 2))
        pa.send_message(msg)                  # byte-identical duplicate...
        pa.send_message(fresh)                # ...sharing a coalescing run
        _crank(clock, 2)
        assert [f[4:8] for f in sent] == [b"\x00\x00\x00\x01"]
        assert h not in decoded               # duplicate dropped pre-decode
        assert got == [X.MessageType.SCP_MESSAGE]   # only the fresh one
        assert ob.stats["deduped"] == dedup0 + 1
        assert pb._processed_since_grant == earned0 + 2  # both debited
        assert pb in ob.floodgate.peers_told(h)
        assert pa.state == pa.GOT_AUTH and pb.state == pb.GOT_AUTH

    def test_flow_control_debits_per_message_not_per_frame(self):
        clock, pa, pb = self._pair()
        sk_a = SecretKey(b"\x91" * 32)
        cap0 = pa._outbound_capacity
        for slot in range(3):
            pa.send_message(X.StellarMessage.envelope(
                self._envelope(sk_a, slot)))
        # all three ride one pending run, yet capacity fell by three
        assert pa._outbound_capacity == cap0 - 3
        pa._outbound_capacity = 0
        pa.send_message(X.StellarMessage.envelope(self._envelope(sk_a, 9)))
        assert pa.flood_queue_len == 1       # over-cap message queued

    def test_receiver_earns_grant_credit_per_contained_message(self):
        clock, pa, pb = self._pair()
        sk_a = SecretKey(b"\x91" * 32)
        before = pb._processed_since_grant
        for slot in range(3):
            pa.send_message(X.StellarMessage.envelope(
                self._envelope(sk_a, slot)))
        _crank(clock, 3)
        assert pb._processed_since_grant == before + 3

    def test_send_more_flushes_pending_run_first(self):
        """SEND_MORE[_EXTENDED] is latency-immediate: a (deferred) grant
        release drains the coalescing queue ahead of itself, keeping
        frame order == send order."""
        clock, pa, pb = self._pair()
        sent = self._capture_frames(pa)
        sk_a = SecretKey(b"\x91" * 32)
        for slot in range(2):
            pa.send_message(X.StellarMessage.envelope(
                self._envelope(sk_a, slot)))
        assert sent == []                    # still coalescing
        pa.send_message(X.StellarMessage.sendMoreMessage(
            X.SendMore(numMessages=5)))
        assert len(sent) == 2
        assert sent[0][4:8] == b"\x00\x00\x00\x01"   # the batch, first
        assert sent[1][4:8] == b"\x00\x00\x00\x00"   # then the grant

    def test_size_cap_forces_flush(self):
        clock, pa, pb = self._pair()
        pa._batch_max_msgs = 4
        sent = self._capture_frames(pa)
        got = self._capture_received(pb)
        for i in range(9):
            pa.send_message(X.StellarMessage.getSCPLedgerSeq(i + 1))
        # two full runs of 4 flushed at the cap, the ninth rides the edge
        assert len(sent) == 2
        _crank(clock, 2)
        assert len(sent) == 3
        assert [f[4:8] for f in sent] == [b"\x00\x00\x00\x01"] * 2 \
            + [b"\x00\x00\x00\x00"]
        assert got.count(X.MessageType.GET_SCP_STATE) == 9

    def test_batched_reorder_is_benign_intra_batch(self):
        """Companion to test_reordered_frames_break_auth_sequence: inside
        one batch frame a reorder draw only swaps contained bodies — one
        frame, one sequence number, link stays healthy."""
        clock, pa, pb = self._pair()
        got = self._capture_received(pb)
        pa.reorder_probability = 1.0
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(1))
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(2))
        pa.reorder_probability = 0.0
        _crank(clock, 5)
        assert pa.is_authenticated() and pb.is_authenticated()
        assert got.count(X.MessageType.GET_SCP_STATE) == 2

    def test_batch_drop_burns_sequence_and_fail_stops(self):
        """A dropped batch loses the whole frame but still advances the
        sender's sequence — the next frame hits the same seq-gap
        fail-stop a dropped per-message frame causes."""
        clock, pa, pb = self._pair()
        pa.drop_probability = 1.0
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(1))
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(2))
        _crank(clock, 2)                 # flush draws drop per message
        pa.drop_probability = 0.0
        assert pa._send_seq > pb._recv_seq       # the gap exists
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(3))
        _crank(clock, 2)
        assert pb.state == pb.CLOSING or pa.state == pa.CLOSING


class TestBatchMetrics:
    """overlay.batch.{messages,flush,bytes} are canonical and send-side
    only: they FIRE when a run coalesces and stay QUIET on an unbatched
    link (run-of-one floor frames are classic v0, so they never mark)."""

    def _deltas(self, fn):
        from stellar_core_tpu.util import metrics
        reg = metrics.registry()
        names = ("overlay.batch.messages", "overlay.batch.flush",
                 "overlay.batch.bytes")
        def counts():
            return {"overlay.batch.bytes": reg.counter(
                        "overlay.batch.bytes").value,
                    "overlay.batch.messages": reg.meter(
                        "overlay.batch.messages").count,
                    "overlay.batch.flush": reg.meter(
                        "overlay.batch.flush").count}
        before = counts()
        fn()
        after = counts()
        return {n: after[n] - before[n] for n in names}

    def test_batch_metric_names_are_canonical(self):
        from stellar_core_tpu.util import metrics
        for n in ("overlay.batch.messages", "overlay.batch.flush",
                  "overlay.batch.bytes"):
            assert n in metrics.CANONICAL_METRICS
            assert metrics.METRIC_NAME_RE.match(n)

    def test_metrics_fire_on_coalesced_flush(self):
        helper = TestBatchedTransport()
        clock, pa, pb = helper._pair()

        def burst():
            for i in range(3):
                pa.send_message(X.StellarMessage.getSCPLedgerSeq(i + 1))
            _crank(clock, 2)
        d = self._deltas(burst)
        assert d["overlay.batch.messages"] >= 3
        assert d["overlay.batch.flush"] >= 1
        assert d["overlay.batch.bytes"] > 0

    def test_metrics_quiet_on_unbatched_link_and_floor(self):
        helper = TestBatchedTransport()
        clock, pa, pb = helper._pair(batch_a=True, batch_b=False)
        clock2, pa2, pb2 = helper._pair()

        def quiet_traffic():
            # unbatched link: plain frames only
            for i in range(3):
                pa.send_message(X.StellarMessage.getSCPLedgerSeq(i + 1))
            _crank(clock, 2)
            # batched link, lone message: the run-of-one floor emits a
            # classic v0 frame — batch metrics must not mark
            pa2.send_message(X.StellarMessage.getSCPLedgerSeq(7))
            _crank(clock2, 2)
        d = self._deltas(quiet_traffic)
        assert d == {"overlay.batch.messages": 0,
                     "overlay.batch.flush": 0,
                     "overlay.batch.bytes": 0}


class TestBatchedTransportOverTCP:
    def test_mixed_mode_fleet_interoperates(self, monkeypatch):
        """A batching node must close ledgers with an unbatched peer over
        real TCP — the AUTH flag downgrade is per-link, so a mixed fleet
        reaches externalize with no fork."""
        from stellar_core_tpu.herder import herder as herder_mod
        monkeypatch.setattr(herder_mod, "EXP_LEDGER_TIMESPAN_SECONDS", 0.3)
        clock = VirtualClock(ClockMode.REAL_TIME)
        sks = [SecretKey(bytes([0x1a + i]) * 32) for i in range(3)]
        ids = [s.public_key.ed25519 for s in sks]
        q = qset_of(ids, 2)
        nodes, transports = [], []
        for i, s in enumerate(sks):
            h, o = _make_node(clock, s, q, bytes([0x51 + i]) * 32)
            o.batching = (i != 2)    # node 2 runs the unbatched HEAD mode
            transports.append(TCPTransport(o, listen_port=0))
            nodes.append((h, o))
        try:
            for i in range(3):
                for j in range(i + 1, 3):
                    transports[i].connect("127.0.0.1",
                                          nodes[j][1].listening_port)
            ok = clock.crank_until(
                lambda: all(o.num_authenticated() >= 2 for _, o in nodes),
                timeout=10)
            assert ok, [o.num_authenticated() for _, o in nodes]
            for h, _ in nodes:
                h.bootstrap()
            ok = clock.crank_until(
                lambda: all(h.lm.last_closed_ledger_seq >= 3
                            for h, _ in nodes), timeout=30)
            assert ok, [h.lm.last_closed_ledger_seq for h, _ in nodes]
            hashes = {h.lm.lcl_hash for h, _ in nodes}
            assert len(hashes) == 1, "fork in mixed-mode fleet"
        finally:
            for t in transports:
                t.close()
