"""Declarative SLOs with burn-rate tracking (ISSUE 16): objective
verdicts, the burning latch + flight events, budget assertions, the
/slo admin endpoint, and Prometheus exposition of every new
observability metric name.
"""

import json
import urllib.error
import urllib.request

import pytest

from stellar_core_tpu.util import eventlog, metrics
from stellar_core_tpu.util.slo import (Objective, SLOTracker,
                                       default_objectives)


@pytest.fixture(autouse=True)
def _fresh_state():
    metrics.reset_registry()
    eventlog.event_log().clear()
    yield


def _snap(p99):
    return {"ledger.ledger.close": {"p99_s": p99}}


def _tracker(budget=0.5, window=4, threshold=0.2):
    return SLOTracker([Objective(
        "close-p99", "ledger.ledger.close", "p99_s",
        threshold=threshold, budget=budget, window=window)],
        source="test")


class TestObjective:
    def test_comparison_directions(self):
        lat = Objective("l", "m", "f", 1.0, "<=")
        assert lat.met(1.0) and lat.met(0.5) and not lat.met(1.5)
        rate = Objective("r", "m", "f", 20.0, ">=")
        assert rate.met(20.0) and rate.met(99.0) and not rate.met(5.0)
        with pytest.raises(ValueError):
            Objective("x", "m", "f", 1.0, "==").met(1.0)


class TestBurnTracking:
    def test_burn_flip_records_flight_event_and_counter(self):
        t = _tracker(budget=0.5, window=4)
        for _ in range(2):
            t.evaluate(_snap(0.1))      # healthy
        assert not t.burning("close-p99")
        for _ in range(3):
            t.evaluate(_snap(0.9))      # breaching
        assert t.burning("close-p99")
        assert not t.within_budget()
        assert t.burn_rate("close-p99") > 0.5
        events = [e for e in eventlog.event_log().snapshot()
                  if e["msg"] == "slo burn started"]
        assert len(events) == 1
        ev = events[0]
        assert ev["partition"] == "Perf"
        assert ev["severity"] == "WARNING"
        assert ev["fields"]["objective"] == "close-p99"
        assert ev["fields"]["source"] == "test"
        assert metrics.registry().snapshot()[
            "slo.burn.flips"]["count"] == 1

    def test_burn_clears_when_window_recovers(self):
        t = _tracker(budget=0.5, window=4)
        for _ in range(4):
            t.evaluate(_snap(0.9))
        assert t.burning("close-p99")
        for _ in range(4):
            t.evaluate(_snap(0.05))     # window rolls over to healthy
        assert not t.burning("close-p99")
        assert t.within_budget()
        msgs = [e["msg"] for e in eventlog.event_log().snapshot()
                if e["msg"].startswith("slo burn")]
        assert msgs == ["slo burn started", "slo burn cleared"]
        assert metrics.registry().snapshot()[
            "slo.burn.flips"]["count"] == 2

    def test_absent_metric_is_skipped_not_breached(self):
        t = _tracker()
        out = t.evaluate({"something.else": {"value": 1}})
        assert out == {}
        assert t.burn_rate("close-p99") == 0.0
        assert t.within_budget()

    def test_burn_gauge_exported(self):
        t = _tracker(budget=0.5, window=4)
        for _ in range(4):
            t.evaluate(_snap(0.9))
        snap = metrics.registry().snapshot()
        assert snap["slo.objective.close-p99"]["value"] == 1.0
        assert snap["slo.eval.windows"]["count"] == 4

    def test_report_curve(self):
        t = _tracker(window=4)
        for i, v in enumerate((0.1, 0.3, 0.2)):
            t.evaluate(_snap(v), now=float(i))
        rep = t.report()
        obj = rep["objectives"]["close-p99"]
        assert obj["evaluations"] == 3
        assert obj["breaches"] == 1
        assert obj["curve"] == [[0.0, 0.1], [1.0, 0.3], [2.0, 0.2]]
        assert obj["last_value"] == 0.2
        assert rep["source"] == "test"

    def test_default_objectives_cover_close_admission_catchup(self):
        objs = {o.name: o for o in default_objectives()}
        assert set(objs) == {"close-p99", "admission-p99",
                             "catchup-rate"}
        assert objs["close-p99"].metric == "ledger.ledger.close"
        assert objs["catchup-rate"].comparison == ">="


NEW_METRICS = [
    "fleet.trace.marks", "fleet.trace.merge", "fleet.scrape.polls",
    "fleet.scrape.errors", "profile.sampler.samples",
    "profile.sampler.dropped", "profile.sampler.running",
    "slo.eval.windows", "slo.burn.flips",
]


class TestExposition:
    def test_every_new_metric_name_is_canonical_and_renders(self):
        """All ISSUE 16 metric names are registered canonical names and
        appear in the Prometheus exposition once touched."""
        from stellar_core_tpu.util.metrics import (CANONICAL_METRICS,
                                                   CANONICAL_PREFIXES,
                                                   render_prometheus)
        for name in NEW_METRICS:
            assert name in CANONICAL_METRICS, name
        assert any(p.startswith("slo.objective.")
                   for p in CANONICAL_PREFIXES)
        reg = metrics.registry()
        # touch every name with its proper kind
        reg.counter("fleet.trace.marks").inc()
        reg.timer("fleet.trace.merge").update(0.01)
        reg.counter("fleet.scrape.polls").inc()
        reg.counter("fleet.scrape.errors").inc()
        reg.counter("profile.sampler.samples").inc()
        reg.counter("profile.sampler.dropped").inc()
        class _Box:
            value = 1.0
        box = _Box()
        reg.weak_gauge("profile.sampler.running", box,
                       lambda b: b.value)
        reg.counter("slo.eval.windows").inc()
        reg.counter("slo.burn.flips").inc()
        reg.weak_gauge("slo.objective.close-p99", box,
                       lambda b: b.value)
        text = render_prometheus(reg.snapshot())
        for name in NEW_METRICS + ["slo.objective.close-p99"]:
            prom = name.replace(".", "_").replace("-", "_")
            assert prom in text, f"{name} missing from exposition"


class TestSLOEndpoint:
    @pytest.fixture()
    def app_http(self, slo_cadence=1.0):
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.main.config import Config
        from stellar_core_tpu.main.http_admin import CommandHandler
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock

        cfg = Config.from_dict({
            "NETWORK_PASSPHRASE": "slo test net",
            "RUN_STANDALONE": True,
            "PEER_PORT": 0,
            "SLO_EVAL_CADENCE_S": slo_cadence,
            "SLO_CLOSE_P99_S": 10.0,
        })
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        app = Application(cfg, clock=clock, listen=False)
        http = CommandHandler(app, 0)
        http.start()
        app.start()
        assert clock.crank_until(
            lambda: app.lm.last_closed_ledger_seq >= 3, timeout=60)
        try:
            yield app, clock, http.port
        finally:
            http.stop()
            app.stop()

    def _get(self, port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10.0) as r:
            return json.loads(r.read())

    def test_slo_endpoint_reports_objectives(self, app_http):
        app, clock, port = app_http
        assert app.slo_tracker is not None
        doc = self._get(port, "/slo")
        assert doc["source"] == "local"
        assert set(doc["objectives"]) == {"close-p99", "admission-p99",
                                          "catchup-rate"}
        # the virtual-time crank drove the evaluation timer: the close
        # objective saw real close latencies and stayed healthy
        close = doc["objectives"]["close-p99"]
        assert close["evaluations"] >= 1
        assert doc["ok"] is True

    def test_slo_endpoint_404_when_unconfigured(self):
        from stellar_core_tpu.main.application import Application
        from stellar_core_tpu.main.config import Config
        from stellar_core_tpu.main.http_admin import CommandHandler
        from stellar_core_tpu.util.clock import ClockMode, VirtualClock

        cfg = Config.from_dict({
            "NETWORK_PASSPHRASE": "slo test net 2",
            "RUN_STANDALONE": True,
            "PEER_PORT": 0,
        })
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        app = Application(cfg, clock=clock, listen=False)
        http = CommandHandler(app, 0)
        http.start()
        try:
            assert app.slo_tracker is None
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}/slo", timeout=10.0)
            assert ei.value.code == 404
        finally:
            http.stop()
            app.stop()
