"""BucketListDB: disk-backed authoritative ledger-entry store (ISSUE 2).

Coverage: on-disk index round-trip + corrupted-file fail-stop, snapshot
consistency across a concurrent ledger close (incl. GC pinning), LRU
entry-cache bound enforcement, and the dict-vs-disk differential — a
multi-checkpoint catchup replay with `in_memory_ledger = false` must
produce bucket-list and header hashes byte-identical to the in-memory
path while `LedgerTxnRoot` holds at most the configured cache size.

Reference model: src/bucket/test/BucketIndexTests.cpp +
BucketListDB-mode LedgerTxnRoot behavior since v21.
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.bucket import (Bucket, BucketList, BucketListStore,
                                     DiskBucketIndex)
from stellar_core_tpu.catchup.catchup import CatchupManager
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.history.archive import FileHistoryArchive
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.simulation.loadgen import LoadGenerator
from stellar_core_tpu.testutils import (TestAccount, create_account_op,
                                        native_payment_op, network_id)

PASSPHRASE = "bucketlistdb test network"
NID = network_id(PASSPHRASE)


def _acct_sk(i):
    return SecretKey(bytes([i]) * 32)


def _acct_entry(i, bal=10 ** 9):
    sk = _acct_sk(i)
    acc = X.AccountEntry(
        accountID=X.AccountID.ed25519(sk.public_key.ed25519),
        balance=bal, seqNum=1)
    return X.LedgerEntry(lastModifiedLedgerSeq=1,
                         data=X.LedgerEntryData.account(acc))


def _key_of(entry) -> bytes:
    return X.ledger_entry_key(entry).to_xdr()


def _test_bucket():
    entries = [_acct_entry(i) for i in range(1, 25)]
    dead = [X.ledger_entry_key(_acct_entry(60))]
    return Bucket.fresh(23, entries[:12], entries[12:], dead), entries


# --- on-disk index ---------------------------------------------------------

def test_disk_index_round_trip(tmp_path):
    """from_bucket (save-time) and build (file-scan) must agree exactly."""
    bucket, entries = _test_bucket()
    store = BucketListStore(str(tmp_path))
    idx = store.ensure(bucket)
    idx2 = DiskBucketIndex.build(idx.path,
                                 expected_hex_hash=bucket.hash().hex())
    assert idx2.keys() == idx.keys()
    assert idx2._offsets == idx._offsets
    assert idx2._dead == idx._dead
    assert idx2.protocol_version == idx.protocol_version == 23
    for e in entries:
        hit = idx2.find(_key_of(e))
        assert hit is not None and not hit[2]
    dead_hit = idx2.find(X.ledger_entry_key(_acct_entry(60)).to_xdr())
    assert dead_hit is not None and dead_hit[2]
    assert idx2.find(_key_of(_acct_entry(99))) is None


def test_disk_index_corrupt_file_fail_stop(tmp_path):
    """A flipped byte or truncation must raise at index build, never serve
    lookups (reference: the hash-verify on bucket adoption)."""
    bucket, _ = _test_bucket()
    store = BucketListStore(str(tmp_path))
    idx = store.ensure(bucket)
    data = bytearray(open(idx.path, "rb").read())
    data[len(data) // 2] ^= 0x01
    open(idx.path, "wb").write(bytes(data))
    with pytest.raises(RuntimeError, match="hash check"):
        DiskBucketIndex.build(idx.path,
                              expected_hex_hash=bucket.hash().hex())
    open(idx.path, "wb").write(bytes(data[:-7]))  # truncated record
    with pytest.raises(RuntimeError):
        DiskBucketIndex.build(idx.path,
                              expected_hex_hash=bucket.hash().hex())


def test_store_index_for_missing_file_raises(tmp_path):
    store = BucketListStore(str(tmp_path))
    with pytest.raises(RuntimeError, match="missing bucket file"):
        store.index_for("ab" * 32)


def test_snapshot_pin_blocks_gc(tmp_path):
    bucket, entries = _test_bucket()
    store = BucketListStore(str(tmp_path))
    bl = BucketList()
    bl.levels[0].curr = bucket
    snap = bl.snapshot(1, store=store)
    assert store.gc([]) == 0          # pinned: survives an empty keep-set
    assert snap.load(_key_of(entries[0])) is not None
    snap.release()
    assert store.gc([]) == 1          # released: reclaimed
    assert snap.release() is None     # idempotent


# --- disk-backed manager ---------------------------------------------------

def _spin_up(store=None, cache=None, n_accounts=24):
    mgr = LedgerManager(NID, bucket_store=store, entry_cache_size=cache)
    mgr.start_new_ledger()
    sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.account_key_xdr(sk.public_key.ed25519))
    root = TestAccount(mgr, sk, e.data.value.seqNum)
    sks = [_acct_sk(i + 1) for i in range(n_accounts)]
    mgr.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(s.public_key.ed25519), 10 ** 11)
        for s in sks])], 1000)
    accounts = []
    for s in sks:
        ent = mgr.root.get_entry(X.account_key_xdr(s.public_key.ed25519))
        accounts.append(TestAccount(mgr, s, ent.data.value.seqNum))
    return mgr, root, accounts


def test_snapshot_consistent_across_ledger_close(tmp_path):
    """A snapshot taken before a close keeps serving the OLD state — and
    its pinned files survive GC — until released (reference: the
    BucketSnapshotManager contract for query-server threads)."""
    store = BucketListStore(str(tmp_path))
    mgr, root, accounts = _spin_up(store=store, cache=64)
    a, b = accounts[0], accounts[1]
    kb = X.account_key_xdr(a.secret.public_key.ed25519)
    seq0 = mgr.last_closed_ledger_seq
    snap = mgr.bucket_list.snapshot(seq0, store=store)
    bal0 = snap.load(kb).data.value.balance
    # ten closes move balances and roll level-0 files; force GC past the
    # cadence boundary
    for i in range(10):
        mgr.close_ledger(
            [a.tx([native_payment_op(b.account_id, 1_000_000)])],
            2000 + i)
    store.gc(mgr.bucket_list.referenced_hashes())
    assert snap.load(kb).data.value.balance == bal0       # old view intact
    new_bal = mgr.root.get_entry(kb).data.value.balance
    assert new_bal == bal0 - 10 * 1_000_000 - 10 * 100    # live view moved
    snap.release()
    # after release the old files are collectable; the live root's own
    # snapshot stays pinned and keeps serving
    store.gc(mgr.bucket_list.referenced_hashes())
    assert mgr.root.get_entry(kb).data.value.balance == new_bal


def test_lru_cache_bound_enforced(tmp_path):
    """LedgerTxnRoot in BucketListDB mode never holds more than the
    configured entry count, whatever the traffic (ISSUE 2 acceptance)."""
    store = BucketListStore(str(tmp_path))
    mgr, root, accounts = _spin_up(store=store, cache=8, n_accounts=24)
    assert mgr.root.disk_backed
    import random
    rng = random.Random(7)
    for i in range(12):
        frames = []
        for _ in range(6):
            src = accounts[rng.randrange(len(accounts))]
            dst = accounts[rng.randrange(len(accounts))]
            frames.append(src.tx([native_payment_op(
                dst.account_id, 1000 + rng.randrange(1000))]))
        mgr.close_ledger(frames, 3000 + i)
        assert len(mgr.root._cache) <= 8
    stats = mgr.root.cache_stats()
    assert stats["max_size"] == 8 and stats["size"] <= 8
    assert stats["hits"] + stats["misses"] > 0


def test_dict_vs_disk_close_differential(tmp_path):
    """Same traffic, both root flavors: every per-ledger header hash (and
    therefore every bucketListHash) must be byte-identical."""
    import random

    def run(store=None, cache=None):
        mgr, root, accounts = _spin_up(store=store, cache=cache)
        rng = random.Random(11)
        hashes = [mgr.lcl_hash]
        for i in range(40):
            frames = []
            for _ in range(5):
                src = accounts[rng.randrange(len(accounts))]
                dst = accounts[rng.randrange(len(accounts))]
                frames.append(src.tx([native_payment_op(
                    dst.account_id, 500 + rng.randrange(10 ** 5))]))
            mgr.close_ledger(frames, 5000 + 5 * i)
            hashes.append(mgr.lcl_hash)
        return mgr, hashes

    m_mem, h_mem = run()
    m_disk, h_disk = run(store=BucketListStore(str(tmp_path)), cache=16)
    assert h_mem == h_disk
    assert m_disk.root.disk_backed and not m_mem.root.disk_backed
    assert m_mem.lcl_header.bucketListHash == m_disk.lcl_header.bucketListHash
    assert m_mem.root.entry_count() == m_disk.root.entry_count()
    assert len(m_disk.root._cache) <= 16


def test_prefetch_bulk_loads_into_cache(tmp_path):
    store = BucketListStore(str(tmp_path))
    mgr, root, accounts = _spin_up(store=store, cache=64)
    # fresh disk root over the same list: cold cache
    cold = mgr._make_disk_root(mgr.lcl_header)
    keys = [X.account_key_xdr(a.secret.public_key.ed25519)
            for a in accounts[:10]]
    absent = X.account_key_xdr(_acct_sk(200).public_key.ed25519)
    n = cold.prefetch(keys + [absent])
    assert n == 11
    h0 = cold._cache.hits
    for kb in keys:
        assert cold.get_entry(kb) is not None
    assert cold.get_entry(absent) is None       # negative result cached
    assert cold._cache.hits == h0 + 11
    assert cold.prefetch(keys) == 0             # all cached: no probes
    cold.release_snapshot()


# --- catchup replay differential (the acceptance bar) ----------------------

@pytest.fixture(scope="module")
def published(tmp_path_factory):
    """A multi-checkpoint synthetic chain (boundary at >= 127)."""
    archive_dir = tmp_path_factory.mktemp("bldb-archive")
    mgr = LedgerManager(NID)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(archive_dir))
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=7)
    gen.create_accounts(30, per_ledger=10)
    gen.payment_ledgers(30, txs_per_ledger=6)
    gen.run_to_checkpoint_boundary()
    while len(history.published_checkpoints) < 2:
        gen.payment_ledgers(10, txs_per_ledger=6)
        gen.run_to_checkpoint_boundary()
    return archive, mgr


def test_catchup_replay_disk_matches_in_memory(published, tmp_path):
    """ISSUE 2 acceptance: with in_memory_ledger = false a full catchup
    replay produces bucket-list and header hashes byte-identical to the
    in-memory path, with the root bounded by the LRU size throughout."""
    archive, live = published
    cm_mem = CatchupManager(NID, PASSPHRASE, native=False)
    m_mem = cm_mem.catchup_complete(archive)

    store = BucketListStore(str(tmp_path))
    cm_disk = CatchupManager(NID, PASSPHRASE, native=False,
                             bucket_store=store, entry_cache_size=32)
    m_disk = cm_disk.catchup_complete(archive)

    assert m_disk.root.disk_backed
    assert m_disk.lcl_hash == m_mem.lcl_hash == live.lcl_hash
    assert m_disk.lcl_header.bucketListHash == \
        m_mem.lcl_header.bucketListHash
    assert m_disk.bucket_list.hash() == m_mem.bucket_list.hash()
    assert len(m_disk.root._cache) <= 32
    assert m_disk.root.entry_count() == m_mem.root.entry_count()
    # spot-check entry-level equality through both read paths
    for kb in list(m_mem.root.all_keys())[:20]:
        assert m_disk.root.get_entry(kb).to_xdr() == \
            m_mem.root.get_entry(kb).to_xdr()


def test_catchup_native_round_trips_disk_root(published, tmp_path):
    """The native engine imports from / exports to a BucketListDB root
    (raw-record seam, no dict): hashes stay identical."""
    from stellar_core_tpu.ledger.native_apply import native_apply_available
    if not native_apply_available():
        pytest.skip("native engine not built")
    archive, live = published
    store = BucketListStore(str(tmp_path))
    cm = CatchupManager(NID, PASSPHRASE, native=True,
                        bucket_store=store, entry_cache_size=32)
    m = cm.catchup_complete(archive)
    assert m.lcl_hash == live.lcl_hash
    assert m.root.disk_backed
    assert len(m.root._cache) <= 32


def test_catchup_minimal_assume_state_disk(published, tmp_path):
    """Assume-state (ApplyBucketsWork analog) in disk mode: no dict is
    materialized, reads come off the archive's indexed bucket files."""
    archive, live = published
    store = BucketListStore(str(tmp_path))
    cm = CatchupManager(NID, PASSPHRASE, bucket_store=store,
                        entry_cache_size=32)
    m = cm.catchup_minimal(archive)
    cm_mem = CatchupManager(NID, PASSPHRASE)
    m_mem = cm_mem.catchup_minimal(archive)
    assert m.root.disk_backed
    assert m.lcl_hash == m_mem.lcl_hash
    assert m.root.entry_count() == m_mem.root.entry_count()
    for kb in list(m_mem.root.all_keys())[:20]:
        assert m.root.get_entry(kb).to_xdr() == \
            m_mem.root.get_entry(kb).to_xdr()


def test_restart_from_disk_mode(tmp_path):
    """Disk-mode node restart: durable sqlite + BucketListStore rebuild an
    identical disk-backed root (crash-only recovery, BucketListDB
    flavor)."""
    from stellar_core_tpu.database import Database
    store = BucketListStore(str(tmp_path / "buckets"))
    db_path = str(tmp_path / "node.db")
    mgr, root, accounts = _spin_up(store=store, cache=32)
    mgr.enable_persistence(Database(db_path), store)
    for i in range(4):
        mgr.close_ledger([accounts[0].tx([native_payment_op(
            accounts[1].account_id, 7_000)])], 9000 + i)
    mgr.db.close()

    db2 = Database(db_path)
    store2 = BucketListStore(str(tmp_path / "buckets"))
    m2 = LedgerManager.load_last_known_ledger(
        NID, db2, store2, bucket_store=store2, entry_cache_size=32)
    assert m2.root.disk_backed
    assert m2.lcl_hash == mgr.lcl_hash
    assert m2.root.entry_count() == mgr.root.entry_count()
    kb = X.account_key_xdr(accounts[1].secret.public_key.ed25519)
    assert m2.root.get_entry(kb).to_xdr() == \
        mgr.root.get_entry(kb).to_xdr()


# --- config + CLI surface --------------------------------------------------

def test_config_bucketlistdb_flags():
    cfg = Config.from_dict({"IN_MEMORY_LEDGER": False,
                            "BUCKETLISTDB_ENTRY_CACHE_SIZE": 512})
    assert cfg.IN_MEMORY_LEDGER is False
    assert cfg.BUCKETLISTDB_ENTRY_CACHE_SIZE == 512
    assert Config().IN_MEMORY_LEDGER is True


def test_explicit_native_request_warns_when_unavailable(caplog):
    """ADVICE r5 low: an explicit native=True that cannot be honored must
    warn loudly, not silently degrade to the ~10x slower Python path."""
    import logging
    from stellar_core_tpu.invariant import InvariantManager
    with caplog.at_level(logging.WARNING):
        cm = CatchupManager(NID, PASSPHRASE, native=True,
                            invariant_manager=InvariantManager())
    assert cm.native is False
    assert any("EXPLICITLY requested" in r.message for r in caplog.records)


def test_bucketlistdb_metrics_recorded(tmp_path):
    """The observability contract: load/prefetch timers, per-level probe
    counters and cache hit/miss meters appear under bucketlistdb.*."""
    from stellar_core_tpu.util.metrics import registry
    store = BucketListStore(str(tmp_path))
    mgr, root, accounts = _spin_up(store=store, cache=16)
    mgr.close_ledger([accounts[0].tx([native_payment_op(
        accounts[1].account_id, 999)])], 7777)
    snap = registry().snapshot(prefix="bucketlistdb.")
    assert "bucketlistdb.load" in snap
    assert "bucketlistdb.cache.hit" in snap
    assert "bucketlistdb.cache.miss" in snap
    assert any(k.startswith("bucketlistdb.probe.level-") for k in snap)
    assert snap["bucketlistdb.cache.hit"]["count"] > 0
