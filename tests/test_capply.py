"""Differential tests for the native apply engine (native/capply.c).

The Python engine is the semantic oracle: every test replays the same
archive through both paths and asserts identical LCL hashes, entry
stores and bucket-list hashes — the same strategy as the cxdr/cquorum
differentials (SURVEY.md §4: CPU-vs-offload bit-equality)."""

import random
import tempfile

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.catchup.catchup import CatchupManager
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.history.archive import FileHistoryArchive
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.ledger.native_apply import (NativeApplyBridge,
                                                  native_apply_available)
from stellar_core_tpu.testutils import (TestAccount, build_tx,
                                        change_trust_op, create_account_op,
                                        make_asset, native_payment_op,
                                        network_id, payment_op)

pytestmark = pytest.mark.skipif(not native_apply_available(),
                                reason="_capply not built (make native)")

NID = network_id("capply differential network")
PASS = "capply differential network"


def _archive(tmp, build_traffic, n_accounts=24):
    """Generate an archive with `build_traffic(close, accounts, root)`."""
    mgr = LedgerManager(NID, invariant_manager=None)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(tmp + "/archive")
    history = HistoryManager(mgr, PASS, [archive])
    root_sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(root_sk.public_key.ed25519))).to_xdr())
    root = TestAccount(mgr, root_sk, e.data.value.seqNum)
    ct = [1_600_000_000]

    def close(frames):
        ct[0] += 5
        history.ledger_closed(mgr.close_ledger(frames, ct[0]))

    sks = [SecretKey(bytes([10 + i]) * 32) for i in range(n_accounts)]
    ops = [create_account_op(X.AccountID.ed25519(sk.public_key.ed25519),
                             10 ** 11) for sk in sks]
    close([root.tx(ops)])
    accounts = []
    for sk in sks:
        entry = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
        accounts.append(TestAccount(mgr, sk, entry.data.value.seqNum))
    build_traffic(close, accounts, root)
    while not history.published_checkpoints or \
            history.published_checkpoints[-1] != mgr.last_closed_ledger_seq:
        close([])
    return archive, mgr


def _assert_replays_agree(archive, mgr):
    cm_py = CatchupManager(NID, PASS, native=False)
    m_py = cm_py.catchup_complete(archive)
    cm_c = CatchupManager(NID, PASS, native=True)
    m_c = cm_c.catchup_complete(archive)
    assert m_py.lcl_hash == mgr.lcl_hash
    assert m_c.lcl_hash == mgr.lcl_hash
    assert m_c.bucket_list.hash() == m_py.bucket_list.hash()
    assert {k: e.to_xdr() for k, e in m_c.root._entries.items()} == \
        {k: e.to_xdr() for k, e in m_py.root._entries.items()}
    return cm_c


def test_payment_traffic_native_equals_python():
    rng = random.Random(3)

    def traffic(close, accounts, root):
        for _ in range(12):
            frames = []
            for _ in range(14):
                a = accounts[rng.randrange(len(accounts))]
                b = accounts[rng.randrange(len(accounts))]
                frames.append(a.tx([native_payment_op(
                    b.account_id, 1000 + rng.randrange(10 ** 6))]))
            close(frames)

    with tempfile.TemporaryDirectory() as d:
        archive, mgr = _archive(d, traffic)
        cm = _assert_replays_agree(archive, mgr)
        # every checkpoint was natively applied (no fallbacks)
        assert cm.stats["native_ledgers_applied"] >= 12


def test_multisig_setoptions_and_failures_native_equals_python():
    """SetOptions signer add/remove, multisig payments, and failing txs
    (underfunded / bad auth) must produce identical results + hashes."""
    rng = random.Random(4)

    def traffic(close, accounts, root):
        extras = {}
        setopts = []
        for i, acct in enumerate(accounts):
            if i % 3 == 0:
                extra = SecretKey(bytes([99 + i]) * 32)
                extras[i] = extra
                setopts.append(acct.tx([X.Operation(
                    body=X.OperationBody.setOptionsOp(X.SetOptionsOp(
                        signer=X.Signer(
                            key=X.SignerKey.ed25519(
                                extra.public_key.ed25519),
                            weight=1))))]))
        close(setopts)
        for _ in range(8):
            frames = []
            for _ in range(10):
                i = rng.randrange(len(accounts))
                acct = accounts[i]
                op = native_payment_op(
                    accounts[rng.randrange(len(accounts))].account_id,
                    1000 + rng.randrange(10 ** 6))
                if i in extras:
                    frames.append(build_tx(NID, acct.secret,
                                           acct.next_seq(), [op],
                                           extra_signers=[extras[i]]))
                else:
                    frames.append(acct.tx([op]))
            # a deliberately failing tx: overdrawn payment
            a = accounts[rng.randrange(len(accounts))]
            frames.append(a.tx([native_payment_op(
                accounts[0].account_id, 10 ** 18)]))
            # and a wrongly-signed one (signed by an unrelated key)
            b = accounts[rng.randrange(len(accounts))]
            stranger = SecretKey(bytes([210]) * 32)
            frames.append(build_tx(NID, b.secret, b.next_seq(),
                                   [native_payment_op(
                                       accounts[1].account_id, 1000)],
                                   signers=[stranger]))
            close(frames)
        # remove some signers again
        removals = []
        for i, extra in list(extras.items())[:3]:
            removals.append(accounts[i].tx([X.Operation(
                body=X.OperationBody.setOptionsOp(X.SetOptionsOp(
                    signer=X.Signer(
                        key=X.SignerKey.ed25519(extra.public_key.ed25519),
                        weight=0))))]))
        close(removals)

    with tempfile.TemporaryDirectory() as d:
        archive, mgr = _archive(d, traffic)
        _assert_replays_agree(archive, mgr)


def test_mixed_unsupported_traffic_falls_back_mid_stream():
    """Checkpoints containing ops outside the native set (pool-share
    trustlines) force the per-checkpoint Python fallback; the
    export/import round trips must be hash-exact.  Trustline, payment AND
    offer traffic is NATIVE as of the r5 widening and must not fall
    back."""
    from stellar_core_tpu.testutils import (change_trust_pool_op,
                                            manage_sell_offer_op)

    rng = random.Random(5)

    def traffic(close, accounts, root):
        issuer = accounts[0]
        asset = make_asset("USD", issuer.account_id)
        # checkpoint 1: payments + trustlines + credit payments — ALL
        # native-appliable after the r5 widening
        for _ in range(4):
            close([a.tx([native_payment_op(accounts[2].account_id, 5000)])
                   for a in accounts[3:9]])
        for batch in range(2):
            close([a.tx([change_trust_op(asset)])
                   for a in accounts[10 + 5 * batch:15 + 5 * batch]])
        close([issuer.tx([payment_op(accounts[11].account_id, asset,
                                     70000)])])
        # offers are native too (r5): rest one + cross it partially
        close([accounts[11].tx([manage_sell_offer_op(
            asset, X.Asset.native(), 500, 1, 2)])])
        close([accounts[12].tx([change_trust_op(asset)]),
               accounts[13].tx([change_trust_op(asset)])])
        close([accounts[12].tx([manage_sell_offer_op(
            X.Asset.native(), asset, 300, 2, 1)])])
        # unsupported traffic: a pool-share trustline (python fallback)
        close([accounts[14].tx([change_trust_pool_op(
            X.Asset.native(), asset)])])
        # ... 60+ more native-only ledgers so a later whole checkpoint is
        # native again after the fallback one
        for _ in range(66):
            a = accounts[rng.randrange(3, 9)]
            close([a.tx([native_payment_op(
                accounts[rng.randrange(3, 9)].account_id, 777)])])

    with tempfile.TemporaryDirectory() as d:
        archive, mgr = _archive(d, traffic)
        cm = _assert_replays_agree(archive, mgr)
        assert cm.stats["native_ledgers_applied"] > 0


def test_preauth_and_hashx_signers_native():
    """Preauth-tx signers (consumed on use, sponsorship-aware removal) and
    hashX signers run through the native checker identically."""
    def traffic(close, accounts, root):
        a, b = accounts[0], accounts[1]
        # preauth: sign a future payment, add its hash as signer, then
        # submit it unsigned-by-master
        future = build_tx(NID, a.secret, a.seq_num + 2,
                          [native_payment_op(b.account_id, 12345)],
                          signers=[])
        close([a.tx([X.Operation(body=X.OperationBody.setOptionsOp(
            X.SetOptionsOp(signer=X.Signer(
                key=X.SignerKey.pre_auth_tx(future.content_hash()),
                weight=1))))])])
        a.next_seq()
        close([future])
        # hashX: preimage-revealing payment
        preimage = b"\x42" * 32
        from stellar_core_tpu.crypto.sha import sha256
        close([b.tx([X.Operation(body=X.OperationBody.setOptionsOp(
            X.SetOptionsOp(signer=X.Signer(
                key=X.SignerKey.hash_x(sha256(preimage)), weight=1))))])])
        hx_tx = build_tx(NID, b.secret, b.next_seq(),
                         [native_payment_op(a.account_id, 999)],
                         signers=[])
        hx_tx.envelope.value.signatures.append(X.DecoratedSignature(
            hint=sha256(preimage)[28:32], signature=preimage))
        close([hx_tx])

    with tempfile.TemporaryDirectory() as d:
        archive, mgr = _archive(d, traffic)
        _assert_replays_agree(archive, mgr)


def test_state_roundtrip_through_engine():
    """import -> export with no applies is the identity on entries,
    buckets and the header."""
    def traffic(close, accounts, root):
        for _ in range(5):
            close([accounts[0].tx([native_payment_op(
                accounts[1].account_id, 1000)])])

    with tempfile.TemporaryDirectory() as d:
        archive, mgr = _archive(d, traffic)
        bridge = NativeApplyBridge(NID)
        bridge.import_from(mgr)
        before_entries = {k: e.to_xdr() for k, e in mgr.root._entries.items()}
        before_hash = mgr.bucket_list.hash()
        before_lcl = mgr.lcl_hash
        m2 = LedgerManager(NID, invariant_manager=None)
        m2.start_new_ledger()
        bridge.export_to_manager(m2)
        assert {k: e.to_xdr() for k, e in m2.root._entries.items()} == \
            before_entries
        assert m2.bucket_list.hash() == before_hash
        assert m2.lcl_hash == before_lcl
        assert m2.lcl_header.to_xdr() == mgr.lcl_header.to_xdr()


def test_engine_rejects_corrupt_records():
    from stellar_core_tpu import _capply

    def traffic(close, accounts, root):
        for _ in range(3):
            close([accounts[0].tx([native_payment_op(
                accounts[1].account_id, 1000)])])

    with tempfile.TemporaryDirectory() as d:
        archive, mgr = _archive(d, traffic)
        cm = CatchupManager(NID, PASS, native=True)
        # corrupt one byte of a transactions file: the native parse or the
        # hash chain must fail-stop, never diverge silently
        import gzip, os
        for dirpath, _, files in os.walk(d):
            for f in files:
                if f.startswith("transactions-"):
                    p = os.path.join(dirpath, f)
                    raw = bytearray(gzip.decompress(open(p, "rb").read()))
                    raw[len(raw) // 2] ^= 0xFF
                    open(p, "wb").write(gzip.compress(bytes(raw)))
                    break
        from stellar_core_tpu.catchup.catchup import CatchupError
        with pytest.raises(CatchupError):
            cm.catchup_complete(archive)


def test_randomized_traffic_differential_fuzz():
    """Deterministic fuzz: random mixes of the widened native op set
    (payments native+credit, trustline create/update/delete, manage-data,
    bump-sequence, set-options signers, account merges) plus deliberate
    failure shapes, replayed through BOTH engines — identical hashes and
    stores on every seed."""
    for seed in (11, 23, 47):
        rng = random.Random(seed)

        def traffic(close, accounts, root, rng=rng):
            issuer = accounts[0]
            asset = make_asset("FZZ", issuer.account_id)
            trusted = set()
            data_names = {}
            merged = set()
            # issuer flags: revocable + clawback so AllowTrust /
            # SetTrustLineFlags / Clawback exercise their real arms
            close([issuer.tx([X.Operation(
                body=X.OperationBody.setOptionsOp(X.SetOptionsOp(
                    setFlags=X.AccountFlags.AUTH_REVOCABLE_FLAG
                    | X.AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG)))])])
            for _ in range(30):
                frames = []
                for _ in range(rng.randrange(1, 6)):
                    alive = [i for i in range(1, len(accounts))
                             if i not in merged]
                    if len(alive) < 3:
                        break
                    i = rng.choice(alive)
                    a = accounts[i]
                    roll = rng.random()
                    if roll < 0.25:
                        j = rng.choice(alive)
                        frames.append(a.tx([native_payment_op(
                            accounts[j].account_id,
                            rng.randrange(1, 10 ** 10))]))
                    elif roll < 0.40:
                        frames.append(a.tx([change_trust_op(
                            asset, limit=rng.randrange(0, 10 ** 12))]))
                        trusted.add(i)
                    elif roll < 0.55 and i in trusted:
                        frames.append(issuer.tx([payment_op(
                            a.account_id, asset,
                            rng.randrange(1, 10 ** 6))]))
                    elif roll < 0.70:
                        name = bytes([97 + rng.randrange(4)]) * 3
                        val = (None if rng.random() < 0.3 and
                               data_names.get((i, name)) else
                               rng.randbytes(8))
                        frames.append(a.tx([X.Operation(
                            body=X.OperationBody.manageDataOp(
                                X.ManageDataOp(dataName=name,
                                               dataValue=val)))]))
                        data_names[(i, name)] = val is not None
                    elif roll < 0.72:
                        frames.append(a.tx([X.Operation(
                            body=X.OperationBody.bumpSequenceOp(
                                X.BumpSequenceOp(bumpTo=rng.randrange(
                                    0, 2 ** 40))))]))
                    elif roll < 0.74:
                        # OP-SOURCED payment: op.sourceAccount != tx
                        # source (distinct signature-check target and
                        # lastModified stamping path); the op source must
                        # co-sign
                        j = rng.choice([x for x in alive if x != i])
                        frames.append(build_tx(
                            NID, a.secret, a.next_seq(),
                            [native_payment_op(
                                accounts[0].account_id, 999,
                                source=accounts[j].account_id)],
                            extra_signers=[accounts[j].secret]))
                    elif roll < 0.78 and i in trusted:
                        which = rng.random()
                        if which < 0.34:
                            frames.append(issuer.tx([X.Operation(
                                body=X.OperationBody.allowTrustOp(
                                    X.AllowTrustOp(
                                        trustor=a.account_id,
                                        asset=X.AssetCode.assetCode4(
                                            b"FZZ\x00"),
                                        authorize=rng.choice((0, 1, 2)))))]))
                        elif which < 0.67:
                            clear = rng.choice((0, 1, 2, 4))
                            sett = rng.choice((0, 1, 2))
                            frames.append(issuer.tx([X.Operation(
                                body=X.OperationBody.setTrustLineFlagsOp(
                                    X.SetTrustLineFlagsOp(
                                        trustor=a.account_id, asset=asset,
                                        clearFlags=clear,
                                        setFlags=sett
                                        if not (sett & clear) else 0)))]))
                        else:
                            frames.append(issuer.tx([X.Operation(
                                body=X.OperationBody.clawbackOp(
                                    X.ClawbackOp(
                                        asset=asset,
                                        from_=X.muxed_from_account_id(
                                            a.account_id),
                                        amount=rng.randrange(
                                            1, 10 ** 5))))]))
                    elif roll < 0.80:
                        frames.append(a.tx([X.Operation(
                            body=X.OperationBody.inflation())]))
                    elif roll < 0.85:
                        extra = SecretKey(rng.randbytes(32))
                        frames.append(a.tx([X.Operation(
                            body=X.OperationBody.setOptionsOp(
                                X.SetOptionsOp(signer=X.Signer(
                                    key=X.SignerKey.ed25519(
                                        extra.public_key.ed25519),
                                    weight=rng.randrange(0, 3)))))]))
                    elif roll < 0.92 and len(alive) > 6 and i > 12:
                        # merge a tail account away (may fail with
                        # HAS_SUB_ENTRIES etc. — failures differential too)
                        j = rng.choice([x for x in alive if x != i])
                        frames.append(a.tx([X.Operation(
                            body=X.OperationBody.destination(
                                X.muxed_from_account_id(
                                    accounts[j].account_id)))]))
                        merged.add(i)
                    else:
                        # deliberate failure: overdrawn payment
                        j = rng.choice(alive)
                        frames.append(a.tx([native_payment_op(
                            accounts[j].account_id, 10 ** 18)]))
                if frames:
                    close(frames)

        with tempfile.TemporaryDirectory() as d:
            archive, mgr = _archive(d, traffic)
            cm = _assert_replays_agree(archive, mgr)
            # the whole fuzz mix is inside the native set: no fallbacks
            assert cm.stats["native_ledgers_applied"] > 20, cm.stats


def test_offer_crossing_differential():
    """Order-book crossing through the native engine: resting offers,
    partial fills, full consumption, passive offers, buy offers, updates
    and deletes — identical results/hashes vs the Python crossing engine
    (the r5 C port of exchangeV10 + convertWithOffers)."""
    from stellar_core_tpu.testutils import (create_passive_sell_offer_op,
                                            manage_buy_offer_op,
                                            manage_sell_offer_op)

    for seed in (7, 19):
        rng = random.Random(seed)

        def traffic(close, accounts, root, rng=rng):
            issuer = accounts[0]
            usd = make_asset("USD", issuer.account_id)
            eur = make_asset("EURO5", issuer.account_id)
            traders = accounts[1:13]
            close([t.tx([change_trust_op(usd)]) for t in traders])
            close([t.tx([change_trust_op(eur)]) for t in traders])
            close([issuer.tx([payment_op(t.account_id, usd, 10 ** 9)])
                   for t in traders[:6]])
            close([issuer.tx([payment_op(t.account_id, eur, 10 ** 9)])
                   for t in traders[6:]])
            pairs = [(X.Asset.native(), usd), (usd, X.Asset.native()),
                     (usd, eur), (eur, usd)]
            for _ in range(26):
                frames = []
                for _ in range(rng.randrange(1, 5)):
                    t = traders[rng.randrange(len(traders))]
                    selling, buying = pairs[rng.randrange(len(pairs))]
                    n = rng.randrange(1, 8)
                    d = rng.randrange(1, 8)
                    amt = rng.randrange(1, 10 ** 6)
                    roll = rng.random()
                    if roll < 0.55:
                        frames.append(t.tx([manage_sell_offer_op(
                            selling, buying, amt, n, d)]))
                    elif roll < 0.75:
                        frames.append(t.tx([manage_buy_offer_op(
                            selling, buying, amt, n, d)]))
                    elif roll < 0.9:
                        frames.append(t.tx([create_passive_sell_offer_op(
                            selling, buying, amt, n, d)]))
                    else:
                        # delete/update a random own offer id (often
                        # NOT_FOUND — failure differential)
                        frames.append(t.tx([manage_sell_offer_op(
                            selling, buying,
                            rng.choice((0, amt)), n, d,
                            offer_id=rng.randrange(1, 60))]))
                if frames:
                    close(frames)

        with tempfile.TemporaryDirectory() as d:
            archive, mgr = _archive(d, traffic)
            cm = _assert_replays_agree(archive, mgr)
            assert cm.stats["native_ledgers_applied"] > 25, cm.stats


def test_offer_deterministic_fill_differential():
    """A deterministic partial + full fill: maker rests 1000 USD @ 2/1,
    taker buys 400 (partial), second taker sweeps the rest (full,
    deleting the offer).  Verifies resting-offer shrink, claim atoms, and
    idPool evolution through the native engine."""
    from stellar_core_tpu.testutils import manage_sell_offer_op

    def traffic(close, accounts, root):
        issuer, maker, t1, t2 = accounts[0], accounts[1], accounts[2], \
            accounts[3]
        usd = make_asset("USD", issuer.account_id)
        close([a.tx([change_trust_op(usd)]) for a in (maker, t1, t2)])
        close([issuer.tx([payment_op(maker.account_id, usd, 10 ** 7)])])
        # maker sells 1000 USD for XLM at price 2 XLM/USD
        close([maker.tx([manage_sell_offer_op(
            usd, X.Asset.native(), 1000, 2, 1)])])
        # taker 1 sells 800 XLM for USD at 1/2 USD-per-XLM -> crosses 400
        close([t1.tx([manage_sell_offer_op(
            X.Asset.native(), usd, 800, 1, 2)])])
        # taker 2 sweeps the remaining 600 with headroom
        close([t2.tx([manage_sell_offer_op(
            X.Asset.native(), usd, 5000, 1, 2)])])

    with tempfile.TemporaryDirectory() as d:
        archive, mgr = _archive(d, traffic)
        cm = _assert_replays_agree(archive, mgr)
        assert cm.stats["native_ledgers_applied"] > 0
        # the maker's USD offer is gone; taker 2's residual XLM offer rests
        offers = [e for e in mgr.root._entries.values()
                  if e.data.switch == X.LedgerEntryType.OFFER]
        assert len(offers) == 1, offers
        rest = offers[0].data.value
        assert rest.selling.switch == X.AssetType.ASSET_TYPE_NATIVE


def test_claimable_balance_differential():
    """Create / claim / clawback claimable balances (native + credit
    assets, conditional predicates, the per-claimant sponsored reserve)
    through the native engine — identical hashes/stores vs the oracle."""
    def traffic(close, accounts, root):
        issuer, a, b, c_ = accounts[0], accounts[1], accounts[2], accounts[3]
        usd = make_asset("USD", issuer.account_id)
        close([issuer.tx([X.Operation(
            body=X.OperationBody.setOptionsOp(X.SetOptionsOp(
                setFlags=X.AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG)))])])
        close([x.tx([change_trust_op(usd)]) for x in (a, b)])
        close([issuer.tx([payment_op(a.account_id, usd, 10 ** 7)])])

        def cb_op(acct, asset, amount, claimants):
            return acct.tx([X.Operation(
                body=X.OperationBody.createClaimableBalanceOp(
                    X.CreateClaimableBalanceOp(
                        asset=asset, amount=amount, claimants=claimants)))])

        uncond = X.ClaimPredicate.unconditional()
        before = X.ClaimPredicate.absBefore(1_600_009_999)
        after_not = X.ClaimPredicate.notPredicate(
            X.ClaimPredicate.absBefore(1))
        # native CB with two claimants (conditional + unconditional)
        close([cb_op(c_, X.Asset.native(), 5_000_000, [
            X.Claimant.v0(X.ClaimantV0(destination=b.account_id,
                                       predicate=before)),
            X.Claimant.v0(X.ClaimantV0(destination=a.account_id,
                                       predicate=after_not))])])
        # credit CB from a clawback-enabled trustline
        close([cb_op(a, usd, 70_000, [
            X.Claimant.v0(X.ClaimantV0(destination=b.account_id,
                                       predicate=uncond))])])
        # b claims the native one (predicate satisfied: closeTime < abs)
        ids = [e.data.value.balanceID
               for e in mgr_entries_cb()]
        # claims happen by scanning current CB entries
        for bid in ids:
            close([b.tx([X.Operation(
                body=X.OperationBody.claimClaimableBalanceOp(
                    X.ClaimClaimableBalanceOp(balanceID=bid)))])])
        # recreate a credit CB and claw it back as the issuer
        close([cb_op(a, usd, 50_000, [
            X.Claimant.v0(X.ClaimantV0(destination=c_.account_id,
                                       predicate=uncond))])])
        bid2 = mgr_entries_cb()[0].data.value.balanceID
        close([issuer.tx([X.Operation(
            body=X.OperationBody.clawbackClaimableBalanceOp(
                X.ClawbackClaimableBalanceOp(balanceID=bid2)))])])
        # a failing claim: wrong claimant
        close([cb_op(c_, X.Asset.native(), 1_000, [
            X.Claimant.v0(X.ClaimantV0(destination=a.account_id,
                                       predicate=uncond))])])
        bid3 = mgr_entries_cb()[0].data.value.balanceID
        close([b.tx([X.Operation(
            body=X.OperationBody.claimClaimableBalanceOp(
                X.ClaimClaimableBalanceOp(balanceID=bid3)))])])

    with tempfile.TemporaryDirectory() as d:
        mgr0 = LedgerManager(NID, invariant_manager=None)
        mgr0.start_new_ledger()

        def mgr_entries_cb():
            return [e for e in mgr0.root._entries.values()
                    if e.data.switch == X.LedgerEntryType.CLAIMABLE_BALANCE]
        archive = FileHistoryArchive(d + "/archive")
        history = HistoryManager(mgr0, PASS, [archive])
        rk = mgr0.root_account_secret()
        e0 = mgr0.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(
                rk.public_key.ed25519))).to_xdr())
        root = TestAccount(mgr0, rk, e0.data.value.seqNum)
        ct = [1_600_000_000]

        def close(frames):
            ct[0] += 5
            history.ledger_closed(mgr0.close_ledger(frames, ct[0]))

        sks = [SecretKey(bytes([140 + i]) * 32) for i in range(4)]
        close([root.tx([create_account_op(
            X.AccountID.ed25519(sk.public_key.ed25519), 10 ** 11)
            for sk in sks])])
        accounts = []
        for sk in sks:
            en = mgr0.root.get_entry(X.LedgerKey.account(
                X.LedgerKeyAccount(accountID=X.AccountID.ed25519(
                    sk.public_key.ed25519))).to_xdr())
            accounts.append(TestAccount(mgr0, sk, en.data.value.seqNum))
        traffic(close, accounts, root)
        while not history.published_checkpoints or \
                history.published_checkpoints[-1] != \
                mgr0.last_closed_ledger_seq:
            close([])
        cm = _assert_replays_agree(archive, mgr0)
        assert cm.stats["native_ledgers_applied"] > 0
        # the whole CB mix must be native (no fallbacks)
        assert cm.stats["native_ledgers_applied"] == \
            mgr0.last_closed_ledger_seq - 1, cm.stats


def test_fee_bump_differential():
    """Fee-bumped transactions through the native engine: outer fee-source
    charging, unconditional inner seq consumption, inner apply with its
    own signatures, txFEE_BUMP_INNER_SUCCESS/FAILED nesting — plus a
    bad-outer-auth bump and a failing inner — identical hashes/stores."""
    def fee_bump(fee_source: TestAccount, inner_frame, fee):
        fb = X.FeeBumpTransaction(
            feeSource=X.muxed_from_account_id(fee_source.account_id),
            fee=fee,
            innerTx=X.FeeBumpInnerTx.v1(inner_frame.envelope.value),
            ext=X.FeeBumpTransaction._spec[3][1].cls(0))
        env = X.TransactionEnvelope.feeBump(
            X.FeeBumpTransactionEnvelope(tx=fb, signatures=[]))
        from stellar_core_tpu.transactions.frame import TransactionFrame
        frame = TransactionFrame.make_from_wire(NID, env)
        env.value.signatures.append(X.DecoratedSignature(
            hint=fee_source.secret.public_key.hint(),
            signature=fee_source.secret.sign(frame.content_hash())))
        return frame

    def traffic(close, accounts, root):
        payer, a, b = accounts[0], accounts[1], accounts[2]
        # successful bump: payer pays the fee for a's payment
        inner = build_tx(NID, a.secret, a.next_seq(),
                         [native_payment_op(b.account_id, 12345)], fee=100)
        close([fee_bump(payer, inner, 400)])
        # failing inner (overdrawn) still consumes a's seq + payer's fee
        inner2 = build_tx(NID, a.secret, a.next_seq(),
                          [native_payment_op(b.account_id, 10 ** 18)],
                          fee=100)
        close([fee_bump(payer, inner2, 400)])
        # bad outer auth: signed by the wrong key
        inner3 = build_tx(NID, a.secret, a.next_seq(),
                          [native_payment_op(b.account_id, 777)], fee=100)
        fb3 = fee_bump(payer, inner3, 400)
        wrong = SecretKey(bytes([230]) * 32)
        fb3.envelope.value.signatures[:] = [X.DecoratedSignature(
            hint=wrong.public_key.hint(),
            signature=wrong.sign(fb3.content_hash()))]
        close([fb3])
        # the inner seq WAS consumed by the failing bump's fee phase...
        # but not by the bad-auth one (its fee phase still ran!) — mirror
        # whatever the oracle does by just continuing with fresh payments
        close([b.tx([native_payment_op(a.account_id, 50)])])

    with tempfile.TemporaryDirectory() as d:
        archive, mgr = _archive(d, traffic)
        cm = _assert_replays_agree(archive, mgr)
        assert cm.stats["native_ledgers_applied"] == \
            mgr.last_closed_ledger_seq - 1, cm.stats
