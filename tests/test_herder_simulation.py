"""Herder + multi-node simulation tests: full consensus rounds closing real
ledgers with real transactions, on virtual time.

Reference test model: src/herder/test/HerderTests.cpp +
src/simulation/test/ — networks of in-process nodes reach consensus,
ledgers close with identical hashes, txs submitted to one node are
externalized everywhere, upgrades apply when voted.
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.herder import (AddResult, HerderState,
                                     UpgradeParameters, Upgrades)
from stellar_core_tpu.simulation import make_core_topology
from stellar_core_tpu.testutils import TestAccount, create_account_op
from stellar_core_tpu.crypto.keys import SecretKey


def make_running_sim(n=4, threshold=None):
    sim = make_core_topology(n, threshold)
    sim.start_all_nodes()
    return sim


class TestConsensusRounds:
    def test_three_nodes_close_empty_ledgers(self):
        sim = make_running_sim(3)
        assert sim.crank_until_ledger(3, timeout=120)
        assert sim.hashes_agree(2)
        assert sim.hashes_agree(3)

    def test_four_nodes_progress_many_ledgers(self):
        sim = make_running_sim(4)
        assert sim.crank_until_ledger(6, timeout=300)
        for seq in range(2, 7):
            assert sim.hashes_agree(seq), f"fork at ledger {seq}"

    def test_ledger_cadence_is_five_seconds(self):
        sim = make_running_sim(3)
        t0 = sim.clock.now()
        assert sim.crank_until_ledger(5, timeout=300)
        elapsed = sim.clock.now() - t0
        # 4 rounds at ~5s each; wide brackets (first round is immediate)
        assert 10.0 <= elapsed <= 60.0, elapsed


class TestTransactionFlow:
    def test_submitted_tx_externalizes_on_all_nodes(self):
        sim = make_running_sim(3)
        node = sim.nodes[0]
        root_sk = node.lm.root_account_secret()
        root_entry = node.lm.root.get_entry(
            X.LedgerKey.account(X.LedgerKeyAccount(
                accountID=X.AccountID.ed25519(
                    root_sk.public_key.ed25519))).to_xdr())
        root = TestAccount(node.lm, root_sk, root_entry.data.value.seqNum)

        dest = SecretKey(b"\x77" * 32)
        frame = root.tx([create_account_op(
            X.AccountID.ed25519(dest.public_key.ed25519), 50_000_000_000)])
        res = node.submit(frame)
        assert res.code == AddResult.STATUS_PENDING

        target = node.lcl + 2
        assert sim.crank_until_ledger(target, timeout=120)
        # the new account must exist on every node
        key = X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(dest.public_key.ed25519))).to_xdr()
        for n in sim.nodes:
            entry = n.lm.root.get_entry(key)
            assert entry is not None, "tx not applied on some node"
            assert entry.data.value.balance == 50_000_000_000
        assert sim.hashes_agree()

    def test_pull_mode_fetch_serves_missing_txset_during_consensus(self):
        """Consensus over the real overlay uses hash-addressed item fetch:
        a validator that hears ballots for a txset it never saw must pull
        it from a peer (reference: Simulation OVER_LOOPBACK exercising
        ItemFetcher/TxSetFrame fetch — VERDICT r2 next #6)."""
        sim = make_running_sim(3)
        node = sim.nodes[0]
        root_sk = node.lm.root_account_secret()
        root_entry = node.lm.root.get_entry(
            X.LedgerKey.account(X.LedgerKeyAccount(
                accountID=X.AccountID.ed25519(
                    root_sk.public_key.ed25519))).to_xdr())
        root = TestAccount(node.lm, root_sk, root_entry.data.value.seqNum)
        # submit directly into node 0's herder WITHOUT flooding, so the
        # txset node 0 proposes is unknown to nodes 1 and 2 until their
        # herders demand it by hash during the SCP round
        dest_pk = SecretKey(b"\x79" * 32).public_key.ed25519
        frame = root.tx([create_account_op(
            X.AccountID.ed25519(dest_pk), 10_000_000_000)])
        saved_flood, node.herder.tx_flood = node.herder.tx_flood, \
            (lambda f: None)
        key = X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(dest_pk))).to_xdr()
        try:
            res = node.submit(frame)
            assert res.code == AddResult.STATUS_PENDING
            # the tx externalizes whenever node 0's nomination wins a
            # round — crank until it lands everywhere (not a fixed count)
            assert sim.crank_until(
                lambda: all(n.lm.root.get_entry(key) is not None
                            for n in sim.nodes), timeout=240)
        finally:
            node.herder.tx_flood = saved_flood
        assert sim.hashes_agree()
        served = sum(n.overlay.stats.get("txsets_served", 0)
                     for n in sim.nodes)
        assert served >= 1, [n.overlay.stats for n in sim.nodes]

    def test_duplicate_submission_rejected(self):
        sim = make_running_sim(3)
        node = sim.nodes[0]
        root_sk = node.lm.root_account_secret()
        root_entry = node.lm.root.get_entry(
            X.LedgerKey.account(X.LedgerKeyAccount(
                accountID=X.AccountID.ed25519(
                    root_sk.public_key.ed25519))).to_xdr())
        root = TestAccount(node.lm, root_sk, root_entry.data.value.seqNum)
        dest = SecretKey(b"\x66" * 32)
        frame = root.tx([create_account_op(
            X.AccountID.ed25519(dest.public_key.ed25519), 10_000_000_000)])
        assert node.submit(frame).code == AddResult.STATUS_PENDING
        assert node.submit(frame).code == AddResult.STATUS_DUPLICATE


class TestUpgradeVoting:
    def test_base_fee_upgrade_applies(self):
        import stellar_core_tpu.simulation.simulation as simmod
        from stellar_core_tpu.crypto.sha import sha256

        sim = simmod.Simulation()
        secrets = [SecretKey(bytes([i + 1]) * 32) for i in range(3)]
        ids = [s.public_key.ed25519 for s in secrets]
        q = simmod.qset_of(ids, 2)
        ups = Upgrades(UpgradeParameters(upgrade_time=0, base_fee=250))
        for s in secrets:
            sim.add_node(s, q, upgrades=ups)
        sim.start_all_nodes()
        assert sim.crank_until_ledger(3, timeout=120)
        for n in sim.nodes:
            assert n.lm.lcl_header.baseFee == 250
        assert sim.hashes_agree()


class TestPartition:
    def test_minority_partition_stalls_then_recovers(self):
        sim = make_running_sim(4, threshold=3)
        assert sim.crank_until_ledger(2, timeout=60)
        # cut one node off: the trio keeps going, the loner stalls
        loner, rest = sim.nodes[0], sim.nodes[1:]
        sim.partition_nodes([[loner], rest])
        start = min(n.lcl for n in rest)
        assert sim.crank_until(lambda: all(n.lcl >= start + 2 for n in rest),
                               timeout=120)
        assert loner.lcl < start + 2
        # heal: the loner hears newer slots and buffers/out-of-syncs; in
        # this transport it catches up via buffered externalize once the
        # missing tx sets are fetchable
        sim.heal_partitions()
        target = max(n.lcl for n in rest) + 2
        assert sim.crank_until(
            lambda: all(n.lcl >= target for n in sim.nodes), timeout=240)
        assert sim.hashes_agree()


class TestQuorumTracking:
    def test_quorum_tracker_sees_all_nodes(self):
        sim = make_running_sim(3)
        assert sim.crank_until_ledger(3, timeout=120)
        for n in sim.nodes:
            assert n.herder.quorum_tracker.node_count == 3


def test_cycle_topology_externalizes():
    """Ring of 2-of-3 neighbour slices reaches consensus (reference:
    Topologies::cycle acceptance tests)."""
    from stellar_core_tpu.simulation.simulation import make_cycle_topology
    sim = make_cycle_topology(4)
    sim.start_all_nodes()
    assert sim.crank_until_ledger(3, timeout=300)
    assert sim.hashes_agree()


def test_hierarchical_topology_externalizes():
    """Tier-1-shaped org hierarchy reaches consensus (reference:
    Topologies::hierarchicalQuorum)."""
    from stellar_core_tpu.simulation.simulation import (
        make_hierarchical_topology)
    sim = make_hierarchical_topology(3, nodes_per_org=3)
    sim.start_all_nodes()
    assert sim.crank_until_ledger(3, timeout=300)
    assert sim.hashes_agree()
