"""Soroban execution subsystem acceptance suite (ISSUE 17).

Covers the bounded host's budget discipline (over-budget → structured
failure, fee charged, state untouched — differential against the same
tx with a sufficient budget), footprint enforcement (out-of-footprint
access fail-stops the TX, never the node, with no crash bundle), TTL
archival (temp eviction, persistent archive + RestoreFootprint,
ExtendFootprintTTL), footprint clustering, and the mixed-traffic
campaign: ≥50 classic+Soroban ledgers closed under serial AND
footprint-parallel apply with byte-identical bucket-list hashes and at
least one ledger fanning ≥4 disjoint write-set clusters.
"""

import os
from dataclasses import replace

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.soroban import (cluster_footprints, network_config,
                                      set_network_config)
from stellar_core_tpu.soroban.storage import contract_data_key, ttl_key
from stellar_core_tpu.testutils import (TestAccount, contract_address,
                                        extend_ttl_op, invoke_op,
                                        make_soroban_data, native_payment_op,
                                        network_id, restore_footprint_op)

NID = network_id("soroban test network")

IHC = X.InvokeHostFunctionResultCode


@pytest.fixture
def mgr():
    m = LedgerManager(NID)
    m.start_new_ledger()
    return m


@pytest.fixture
def root(mgr):
    sk = mgr.root_account_secret()
    acc = mgr.root.get_entry(
        X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
    return TestAccount(mgr, sk, acc.data.value.seqNum)


@pytest.fixture
def short_ttl():
    """Shrink the TTL floors so archival paths run in a handful of
    closes instead of 120."""
    prev = network_config()
    set_network_config(replace(prev, min_temp_entry_ttl=4,
                               min_persistent_entry_ttl=6))
    yield network_config()
    set_network_config(prev)


def _close(mgr, *frames, close_time=None):
    if close_time is None:
        close_time = int(mgr.lcl_header.scpValue.closeTime) + 5
    return mgr.close_ledger(list(frames), close_time)


def _result_of(arts, frame):
    for pair in arts.result_entry.txResultSet.results:
        if pair.transactionHash == frame.content_hash():
            return pair.result
    raise AssertionError("tx not in result set")


def _balance(mgr, account_id: X.AccountID) -> int:
    e = mgr.root.get_entry(X.LedgerKey.account(
        X.LedgerKeyAccount(accountID=account_id)).to_xdr())
    return e.data.value.balance


def _data_entry(mgr, data_key: X.LedgerKey):
    return mgr.root.get_entry(data_key.to_xdr())


def _ttl_entry(mgr, data_key: X.LedgerKey):
    return mgr.root.get_entry(ttl_key(data_key).to_xdr())


def _put_frame(acct, contract, key, value, durability="persistent",
               instructions=1_000_000, footprint=None):
    dur = (X.ContractDataDurability.PERSISTENT
           if durability == "persistent"
           else X.ContractDataDurability.TEMPORARY)
    dk = contract_data_key(contract, key, dur)
    rw = [dk] if footprint is None else footprint
    sd = make_soroban_data(read_write=rw, instructions=instructions)
    return acct.tx([invoke_op(contract, "put",
                              [key, value, X.SCVal.sym(durability)])],
                   fee=1000 + sd.resourceFee, soroban_data=sd), dk


# ---------------------------------------------------------------------------
# bounded host: execution + budget discipline
# ---------------------------------------------------------------------------

class TestBoundedHost:
    def test_put_writes_entry_and_ttl(self, mgr, root):
        c = contract_address(1)
        key = X.SCVal.sym("counter")
        tx, dk = _put_frame(root, c, key, X.SCVal.u64(7))
        arts = _close(mgr, tx)
        res = _result_of(arts, tx)
        assert res.result.switch == X.TransactionResultCode.txSUCCESS
        assert res.result.value[0].value.value.switch == \
            IHC.INVOKE_HOST_FUNCTION_SUCCESS
        entry = _data_entry(mgr, dk)
        assert entry.data.value.val.value == 7
        ttl = _ttl_entry(mgr, dk)
        assert int(ttl.data.value.liveUntilLedgerSeq) == \
            mgr.last_closed_ledger_seq + \
            network_config().min_persistent_entry_ttl - 1

    def test_budget_differential_fee_charged_state_untouched(self, mgr,
                                                             root):
        """The SAME invoke succeeds under a sufficient declared budget
        and yields the structured RESOURCE_LIMIT_EXCEEDED failure under
        a starved one — full fee charged, state untouched either way
        the ledger closes."""
        c = contract_address(2)
        key = X.SCVal.sym("v")
        ok, dk = _put_frame(root, c, key, X.SCVal.u64(1))
        _close(mgr, ok)
        assert _data_entry(mgr, dk).data.value.val.value == 1

        starved, _ = _put_frame(root, c, key, X.SCVal.u64(2),
                                instructions=10)
        before = _balance(mgr, root.account_id)
        arts = _close(mgr, starved)
        res = _result_of(arts, starved)
        assert res.result.switch == X.TransactionResultCode.txFAILED
        assert res.result.value[0].value.value.switch == \
            IHC.INVOKE_HOST_FUNCTION_RESOURCE_LIMIT_EXCEEDED
        # fee charged in full (structured failure, not a free ride):
        # the whole resource fee plus the base inclusion fee
        assert before - _balance(mgr, root.account_id) == \
            starved.tx.ext.value.resourceFee + 100
        # state untouched: the first write survives, the second never
        # landed
        assert _data_entry(mgr, dk).data.value.val.value == 1

    def test_burn_over_declared_instructions_fails_structured(self, mgr,
                                                              root):
        c = contract_address(3)
        declared = 500_000
        sd = make_soroban_data(instructions=declared)
        tx = root.tx([invoke_op(c, "burn", [X.SCVal.u64(declared * 10)])],
                     fee=1000 + sd.resourceFee, soroban_data=sd)
        res = _result_of(_close(mgr, tx), tx)
        assert res.result.switch == X.TransactionResultCode.txFAILED
        assert res.result.value[0].value.value.switch == \
            IHC.INVOKE_HOST_FUNCTION_RESOURCE_LIMIT_EXCEEDED

    def test_out_of_footprint_traps_tx_not_node(self, mgr, root,
                                                tmp_path, monkeypatch):
        """A write to a key missing from the declared footprint traps
        the TX (structured TRAPPED result), the ledger still closes,
        the node closes the NEXT ledger too, and no crash bundle is
        written."""
        crash_dir = str(tmp_path / "crash")
        monkeypatch.setenv("STPU_CRASH_DIR", crash_dir)
        c = contract_address(4)
        undeclared = X.SCVal.sym("sneaky")
        # footprint declares a DIFFERENT key than the one written
        decoy = contract_data_key(c, X.SCVal.sym("decoy"),
                                  X.ContractDataDurability.PERSISTENT)
        tx, _ = _put_frame(root, c, undeclared, X.SCVal.u64(9),
                           footprint=[decoy])
        arts = _close(mgr, tx)
        res = _result_of(arts, tx)
        assert res.result.switch == X.TransactionResultCode.txFAILED
        assert res.result.value[0].value.value.switch == \
            IHC.INVOKE_HOST_FUNCTION_TRAPPED
        assert _data_entry(mgr, contract_data_key(
            c, undeclared, X.ContractDataDurability.PERSISTENT)) is None
        # crash-bundle-free recovery: the node keeps closing ledgers
        pay = root.tx([native_payment_op(root.account_id, 1)])
        assert _result_of(_close(mgr, pay), pay).result.switch == \
            X.TransactionResultCode.txSUCCESS
        assert not os.path.isdir(crash_dir) or not os.listdir(crash_dir)

    def test_explicit_fail_traps(self, mgr, root):
        c = contract_address(5)
        sd = make_soroban_data()
        tx = root.tx([invoke_op(c, "fail", [])],
                     fee=1000 + sd.resourceFee, soroban_data=sd)
        res = _result_of(_close(mgr, tx), tx)
        assert res.result.value[0].value.value.switch == \
            IHC.INVOKE_HOST_FUNCTION_TRAPPED


# ---------------------------------------------------------------------------
# TTL archival: eviction, archive, restore, extend
# ---------------------------------------------------------------------------

class TestTtlArchival:
    def test_temporary_entry_evicted_at_expiry(self, mgr, root, short_ttl):
        c = contract_address(6)
        key = X.SCVal.sym("t")
        tx, dk = _put_frame(root, c, key, X.SCVal.u64(1),
                            durability="temp")
        _close(mgr, tx)
        live_until = int(_ttl_entry(mgr, dk).data.value.liveUntilLedgerSeq)
        assert live_until == mgr.last_closed_ledger_seq + \
            short_ttl.min_temp_entry_ttl - 1
        while mgr.last_closed_ledger_seq <= live_until:
            _close(mgr)
        # evicted entirely: data AND its TTL entry
        assert _data_entry(mgr, dk) is None
        assert _ttl_entry(mgr, dk) is None

    def test_persistent_archives_then_restores(self, mgr, root, short_ttl):
        c = contract_address(7)
        key = X.SCVal.sym("p")
        tx, dk = _put_frame(root, c, key, X.SCVal.u64(5))
        _close(mgr, tx)
        live_until = int(_ttl_entry(mgr, dk).data.value.liveUntilLedgerSeq)
        while mgr.last_closed_ledger_seq <= live_until:
            _close(mgr)
        # archived, not erased: the data entry stays, access reports
        # ENTRY_ARCHIVED
        assert _data_entry(mgr, dk) is not None
        sd = make_soroban_data(read_write=[dk])
        get = root.tx([invoke_op(c, "get",
                                 [key, X.SCVal.sym("persistent")])],
                      fee=1000 + sd.resourceFee, soroban_data=sd)
        res = _result_of(_close(mgr, get), get)
        assert res.result.value[0].value.value.switch == \
            IHC.INVOKE_HOST_FUNCTION_ENTRY_ARCHIVED
        # RestoreFootprint brings it back to life with a fresh TTL
        sd = make_soroban_data(read_write=[dk])
        rest = root.tx([restore_footprint_op()],
                       fee=1000 + sd.resourceFee, soroban_data=sd)
        res = _result_of(_close(mgr, rest), rest)
        assert res.result.switch == X.TransactionResultCode.txSUCCESS
        assert int(_ttl_entry(mgr, dk).data.value.liveUntilLedgerSeq) == \
            mgr.last_closed_ledger_seq + \
            short_ttl.min_persistent_entry_ttl - 1
        # and the value survived archival
        sd = make_soroban_data(read_write=[dk])
        get2 = root.tx([invoke_op(c, "get",
                                  [key, X.SCVal.sym("persistent")])],
                       fee=1000 + sd.resourceFee, soroban_data=sd)
        res = _result_of(_close(mgr, get2), get2)
        assert res.result.switch == X.TransactionResultCode.txSUCCESS

    def test_extend_footprint_ttl(self, mgr, root, short_ttl):
        c = contract_address(8)
        key = X.SCVal.sym("e")
        tx, dk = _put_frame(root, c, key, X.SCVal.u64(3))
        _close(mgr, tx)
        sd = make_soroban_data(read_only=[dk])
        ext = root.tx([extend_ttl_op(extend_to=40)],
                      fee=1000 + sd.resourceFee, soroban_data=sd)
        arts = _close(mgr, ext)
        assert _result_of(arts, ext).result.switch == \
            X.TransactionResultCode.txSUCCESS
        assert int(_ttl_entry(mgr, dk).data.value.liveUntilLedgerSeq) == \
            mgr.last_closed_ledger_seq + 40

    def test_extend_with_readwrite_footprint_is_malformed(self, mgr, root,
                                                          short_ttl):
        c = contract_address(9)
        key = X.SCVal.sym("m")
        tx, dk = _put_frame(root, c, key, X.SCVal.u64(3))
        _close(mgr, tx)
        sd = make_soroban_data(read_write=[dk])
        bad = root.tx([extend_ttl_op(extend_to=40)],
                      fee=1000 + sd.resourceFee, soroban_data=sd)
        res = _result_of(_close(mgr, bad), bad)
        assert res.result.switch == X.TransactionResultCode.txFAILED
        assert res.result.value[0].value.value.switch == \
            X.ExtendFootprintTTLResultCode.EXTEND_FOOTPRINT_TTL_MALFORMED


# ---------------------------------------------------------------------------
# footprint scheduler: clustering units + the acceptance campaign
# ---------------------------------------------------------------------------

class TestFootprintScheduler:
    def test_disjoint_footprints_cluster_separately(self, mgr, root):
        from stellar_core_tpu.crypto.keys import SecretKey
        from stellar_core_tpu.testutils import create_account_op
        sks = [SecretKey(bytes([50 + i]) * 32) for i in range(4)]
        _close(mgr, root.tx([create_account_op(
            X.AccountID.ed25519(sk.public_key.ed25519), 10 ** 11)
            for sk in sks]))
        accts = []
        for sk in sks:
            e = mgr.root.get_entry(X.account_key_xdr(sk.public_key.ed25519))
            accts.append(TestAccount(mgr, sk, e.data.value.seqNum))
        key = X.SCVal.sym("k")
        frames = [
            _put_frame(a, contract_address(20 + i), key, X.SCVal.u64(i))[0]
            for i, a in enumerate(accts)]
        assert len(cluster_footprints(frames)) == 4
        # same contract key everywhere → one cluster
        shared = [
            _put_frame(a, contract_address(30), key, X.SCVal.u64(i))[0]
            for i, a in enumerate(accts)]
        assert len(cluster_footprints(shared)) == 1
        # same SOURCE account → one cluster even with disjoint data keys
        same_src = [
            _put_frame(root, contract_address(40 + i), key,
                       X.SCVal.u64(i))[0]
            for i in range(3)]
        assert len(cluster_footprints(same_src)) == 1

    def test_mixed_campaign_50_ledgers_hash_identity(self):
        """ISSUE 17 acceptance: ≥50 mixed classic+Soroban ledgers,
        byte-identical bucket-list hashes serial vs footprint-parallel,
        ≥4 disjoint clusters concurrent in at least one ledger."""
        from stellar_core_tpu.simulation.loadgen import SorobanMixCampaign
        rep = SorobanMixCampaign().run(n_ledgers=50)
        assert rep["ledgers"] == 50
        assert rep["hashes_identical"] is True
        assert len(rep["bucket_hashes"]) == 50
        assert rep["max_disjoint_clusters"] >= 4

    def test_admission_campaign_soroban_mix(self, tmp_path):
        """The paced admission path carries the Soroban mix end to end:
        invokes are admitted, surge-priced in their own lane and closed
        as the generalized set's second phase."""
        from stellar_core_tpu.simulation.loadgen import AdmissionCampaign
        camp = AdmissionCampaign(24, seed=3, soroban_mix=0.5)
        try:
            rep = camp.run(n_ledgers=5, offered_per_ledger=24)
        finally:
            camp.close()
        assert rep["soroban_offered"] > 0
        assert rep["applied"] > 0
        assert rep["statuses"].get("pending", 0) > 0
