"""VirtualClock/Scheduler/cache tests — the determinism backbone
(reference: src/util/test/TimerTests.cpp, SchedulerTests.cpp)."""

from stellar_core_tpu.util.cache import RandomEvictionCache
from stellar_core_tpu.util.clock import ClockMode, VirtualClock, VirtualTimer
from stellar_core_tpu.util.scheduler import ACTION_DROPPABLE, Scheduler


def test_virtual_timer_fires_in_order():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    fired = []
    for delay in (3.0, 1.0, 2.0):
        t = VirtualTimer(clock)
        t.expires_from_now(delay, lambda d=delay: fired.append(d))
    while clock.crank():
        pass
    assert fired == [1.0, 2.0, 3.0]
    assert clock.now() == 3.0


def test_virtual_timer_cancel():
    clock = VirtualClock()
    fired = []
    t = VirtualTimer(clock)
    t.expires_from_now(1.0, lambda: fired.append(1))
    t.cancel()
    while clock.crank():
        pass
    assert fired == []


def test_crank_until_predicate():
    clock = VirtualClock()
    state = []
    t = VirtualTimer(clock)
    t.expires_from_now(5.0, lambda: state.append("x"))
    assert clock.crank_until(lambda: bool(state), timeout=10.0)
    assert not clock.crank_until(lambda: len(state) > 1, timeout=1.0)


def test_post_action_runs():
    clock = VirtualClock()
    out = []
    clock.post_action(lambda: out.append(1), "q")
    clock.crank()
    assert out == [1]


def test_scheduler_fairness():
    s = Scheduler()
    order = []
    for i in range(3):
        s.enqueue(lambda i=i: order.append(("a", i)), "a")
    s.enqueue(lambda: order.append(("b", 0)), "b")
    s.run_one_batch(max_actions=2)
    # queue b (less serviced) must get a turn before a drains fully
    assert ("b", 0) in order[:2]


def test_scheduler_load_shed():
    import stellar_core_tpu.util.scheduler as sched
    s = Scheduler()
    old = sched.MAX_QUEUE_DEPTH
    sched.MAX_QUEUE_DEPTH = 2
    try:
        for _ in range(5):
            s.enqueue(lambda: None, "q", ACTION_DROPPABLE)
        assert s.size() == 2
        assert s.dropped == 3
    finally:
        sched.MAX_QUEUE_DEPTH = old


def test_random_eviction_cache():
    c = RandomEvictionCache(4)
    for i in range(10):
        c.put(i, i * 10)
    assert len(c) == 4
    present = [i for i in range(10) if i in c]
    assert len(present) == 4
    for i in present:
        assert c.get(i) == i * 10
