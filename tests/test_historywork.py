"""Historywork DAG tests: pipelining, retry-on-corruption, failure modes.

Reference test model: src/historywork + src/catchup tests (WorkTests,
CatchupWork retry behavior) — catchup is built from retryable Work units
and checkpoint k+1's download overlaps checkpoint k's apply.
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.catchup.catchup import CatchupError, CatchupManager
from stellar_core_tpu.history.archive import FileHistoryArchive
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.historywork import (CatchupWork,
                                          GetAndVerifyCheckpointWork)
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.simulation.loadgen import LoadGenerator
from stellar_core_tpu.testutils import network_id
from stellar_core_tpu.util.clock import ClockMode, VirtualClock

PASSPHRASE = "historywork test net"
NID = network_id(PASSPHRASE)


@pytest.fixture(scope="module")
def archive2cp(tmp_path_factory):
    """An archive spanning two checkpoints."""
    d = tmp_path_factory.mktemp("archive")
    mgr = LedgerManager(NID, invariant_manager=None)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(d))
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=5)
    gen.create_accounts(40, per_ledger=20)
    gen.payment_ledgers(70, txs_per_ledger=10)
    while not history.published_checkpoints or \
            history.published_checkpoints[-1] != mgr.last_closed_ledger_seq:
        gen.close_empty_ledger()
    return archive, mgr


def test_dag_catchup_matches_hash(archive2cp):
    archive, mgr = archive2cp
    cm = CatchupManager(NID, PASSPHRASE)
    out = cm.catchup_complete(archive)
    assert out.lcl_hash == mgr.lcl_hash


def test_download_overlaps_apply(archive2cp):
    """Checkpoint 127's download must START before checkpoint 63's apply
    FINISHES (the double-buffering VERDICT r1 asked for)."""
    archive, mgr = archive2cp
    events = []
    orig_get = GetAndVerifyCheckpointWork.on_run

    def traced_get(self):
        events.append(("download", self.checkpoint))
        return orig_get(self)

    from stellar_core_tpu.historywork import works as W
    orig_apply = W.ApplyCheckpointWork.on_run

    def traced_apply(self):
        events.append(("apply-step", self.download.checkpoint))
        return orig_apply(self)

    GetAndVerifyCheckpointWork.on_run = traced_get
    W.ApplyCheckpointWork.on_run = traced_apply
    try:
        cm = CatchupManager(NID, PASSPHRASE)
        out = cm.catchup_complete(archive)
    finally:
        GetAndVerifyCheckpointWork.on_run = orig_get
        W.ApplyCheckpointWork.on_run = orig_apply
    assert out.lcl_hash == mgr.lcl_hash
    dl_127 = events.index(("download", 127))
    apply_63_last = max(i for i, e in enumerate(events)
                        if e == ("apply-step", 63))
    assert dl_127 < apply_63_last, \
        "checkpoint 127 download did not overlap checkpoint 63 apply"


def test_transient_archive_corruption_retries(archive2cp, monkeypatch):
    """A download that fails twice (IO flake) must retry with backoff and
    the catchup still succeed — without restarting from scratch."""
    archive, mgr = archive2cp
    fails = {"n": 0}
    orig = FileHistoryArchive.get_xdr_file

    def flaky(self, path):
        if "ledger" in path and "0000007f" in path and fails["n"] < 2:
            fails["n"] += 1
            return None   # transient: file not there yet
        return orig(self, path)

    monkeypatch.setattr(FileHistoryArchive, "get_xdr_file", flaky)
    cm = CatchupManager(NID, PASSPHRASE)
    out = cm.catchup_complete(archive)
    assert out.lcl_hash == mgr.lcl_hash
    assert fails["n"] == 2


def test_permanent_corruption_fails_cleanly(archive2cp, monkeypatch):
    archive, mgr = archive2cp
    orig = FileHistoryArchive.get_xdr_file

    def broken(self, path):
        if "ledger" in path and "0000007f" in path:
            return None
        return orig(self, path)

    monkeypatch.setattr(FileHistoryArchive, "get_xdr_file", broken)
    cm = CatchupManager(NID, PASSPHRASE)
    with pytest.raises(CatchupError):
        cm.catchup_complete(archive)


def test_partial_target_inside_checkpoint(archive2cp):
    archive, mgr = archive2cp
    cm = CatchupManager(NID, PASSPHRASE)
    out = cm.catchup_complete(archive, to_ledger=70)
    assert out.last_closed_ledger_seq == 70


def test_preverify_collect_timeout_falls_back_to_cpu():
    """A wedged device job must degrade to on-demand CPU verification
    (no cache seeding, loud warning, fresh worker for later groups) —
    never hang the apply cursor (the shared tunnel wedges for real)."""
    import threading
    import time

    from stellar_core_tpu.catchup.catchup import PreverifyPipeline
    from stellar_core_tpu.testutils import network_id

    # the bounded-wait (and therefore wedge-timeout) machinery is the
    # opt-in race profile since ISSUE 14
    pipe = PreverifyPipeline(network_id("wedge net"), 256, profile="race")
    pipe.COLLECT_TIMEOUT_S = 0.05

    # genuine wedge: a REAL submitted job that blocks past the timeout —
    # exercises the ev.wait timeout branch and the worker-generation drop
    release = threading.Event()
    job = pipe._submit(lambda: release.wait(30.0))
    wedged_jobs = pipe._jobs
    pipe._groups[63] = {"job": job, "pks": [], "sigs": [],
                        "msgs": [], "checkpoints": [63]}
    t0 = time.perf_counter()
    pipe.collect(63)           # must return promptly, not block
    assert time.perf_counter() - t0 < 5.0
    assert pipe.stats.get("collect_fallbacks") == 1
    assert pipe._jobs is None and pipe._worker is None  # generation dropped
    # a later healthy dispatch gets a FRESH worker and completes
    done = pipe._submit(lambda: 42)
    assert pipe._jobs is not wedged_jobs
    assert done[1].wait(5.0) and done[0]["result"] == 42
    # a job stranded on the wedged generation's queue: immediate fallback
    # without waiting out the (now long) timeout
    stale_ev = threading.Event()
    pipe._groups[127] = {"job": ({}, stale_ev, wedged_jobs), "pks": [],
                         "sigs": [], "msgs": [], "checkpoints": [127]}
    pipe.COLLECT_TIMEOUT_S = 60.0
    t0 = time.perf_counter()
    pipe.collect(127)
    assert time.perf_counter() - t0 < 1.0   # did NOT wait out the timeout
    assert pipe.stats["collect_fallbacks"] == 2
    # the healthy current worker survived the stale fallback
    ok = pipe._submit(lambda: 7)
    assert ok[1].wait(5.0) and ok[0]["result"] == 7
    # un-wedge the gen-1 worker: it must NOT rebind to the new queue (a
    # revived worker draining the successor's queue would reintroduce
    # concurrent tunnel calls)
    release.set()
    time.sleep(0.1)
    probe = pipe._submit(lambda: 9)
    assert probe[1].wait(5.0) and probe[0]["result"] == 9
    pipe.close()


def test_preverify_disables_after_consecutive_wedges():
    """A permanently dead device must not cost one full timeout per group
    (a long catchup has dozens): after MAX_CONSECUTIVE_WEDGES genuine
    timeouts the pipeline disables itself and later dispatches no-op."""
    import threading

    from stellar_core_tpu.catchup.catchup import PreverifyPipeline
    from stellar_core_tpu.testutils import network_id

    pipe = PreverifyPipeline(network_id("dead net"), 256, profile="race")
    pipe.COLLECT_TIMEOUT_S = 0.05
    for i, cp in enumerate((63, 127)):
        job = pipe._submit(lambda: threading.Event().wait(30.0))  # wedge
        pipe._groups[cp] = {"job": job, "pks": [], "sigs": [],
                            "msgs": [], "checkpoints": [cp]}
        pipe.collect(cp)
    assert pipe._disabled
    assert pipe.stats["collect_fallbacks"] == 2
    # disabled: dispatch registers a collected no-op group (so the apply
    # path does not re-dispatch) and still counts sigs for honest hit-rate
    # accounting; collect is then a no-op
    pipe.dispatch({191: []})
    assert pipe.dispatched(191)
    pipe.collect(191)
    assert pipe.stats.get("sigs_total", 0) == 0   # empty entries: 0 sigs
    assert pipe.stats.get("sigs_shipped", 0) == 0
    pipe.close()
