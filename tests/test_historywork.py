"""Historywork DAG tests: pipelining, retry-on-corruption, failure modes.

Reference test model: src/historywork + src/catchup tests (WorkTests,
CatchupWork retry behavior) — catchup is built from retryable Work units
and checkpoint k+1's download overlaps checkpoint k's apply.
"""

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.catchup.catchup import CatchupError, CatchupManager
from stellar_core_tpu.history.archive import FileHistoryArchive
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.historywork import (CatchupWork,
                                          GetAndVerifyCheckpointWork)
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.simulation.loadgen import LoadGenerator
from stellar_core_tpu.testutils import network_id
from stellar_core_tpu.util.clock import ClockMode, VirtualClock

PASSPHRASE = "historywork test net"
NID = network_id(PASSPHRASE)


@pytest.fixture(scope="module")
def archive2cp(tmp_path_factory):
    """An archive spanning two checkpoints."""
    d = tmp_path_factory.mktemp("archive")
    mgr = LedgerManager(NID, invariant_manager=None)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(str(d))
    history = HistoryManager(mgr, PASSPHRASE, [archive])
    gen = LoadGenerator(mgr, history, seed=5)
    gen.create_accounts(40, per_ledger=20)
    gen.payment_ledgers(70, txs_per_ledger=10)
    while not history.published_checkpoints or \
            history.published_checkpoints[-1] != mgr.last_closed_ledger_seq:
        gen.close_empty_ledger()
    return archive, mgr


def test_dag_catchup_matches_hash(archive2cp):
    archive, mgr = archive2cp
    cm = CatchupManager(NID, PASSPHRASE)
    out = cm.catchup_complete(archive)
    assert out.lcl_hash == mgr.lcl_hash


def test_download_overlaps_apply(archive2cp):
    """Checkpoint 127's download must START before checkpoint 63's apply
    FINISHES (the double-buffering VERDICT r1 asked for)."""
    archive, mgr = archive2cp
    events = []
    orig_get = GetAndVerifyCheckpointWork.on_run

    def traced_get(self):
        events.append(("download", self.checkpoint))
        return orig_get(self)

    from stellar_core_tpu.historywork import works as W
    orig_apply = W.ApplyCheckpointWork.on_run

    def traced_apply(self):
        events.append(("apply-step", self.download.checkpoint))
        return orig_apply(self)

    GetAndVerifyCheckpointWork.on_run = traced_get
    W.ApplyCheckpointWork.on_run = traced_apply
    try:
        cm = CatchupManager(NID, PASSPHRASE)
        out = cm.catchup_complete(archive)
    finally:
        GetAndVerifyCheckpointWork.on_run = orig_get
        W.ApplyCheckpointWork.on_run = orig_apply
    assert out.lcl_hash == mgr.lcl_hash
    dl_127 = events.index(("download", 127))
    apply_63_last = max(i for i, e in enumerate(events)
                        if e == ("apply-step", 63))
    assert dl_127 < apply_63_last, \
        "checkpoint 127 download did not overlap checkpoint 63 apply"


def test_transient_archive_corruption_retries(archive2cp, monkeypatch):
    """A download that fails twice (IO flake) must retry with backoff and
    the catchup still succeed — without restarting from scratch."""
    archive, mgr = archive2cp
    fails = {"n": 0}
    orig = FileHistoryArchive.get_xdr_file

    def flaky(self, path):
        if "ledger" in path and "0000007f" in path and fails["n"] < 2:
            fails["n"] += 1
            return None   # transient: file not there yet
        return orig(self, path)

    monkeypatch.setattr(FileHistoryArchive, "get_xdr_file", flaky)
    cm = CatchupManager(NID, PASSPHRASE)
    out = cm.catchup_complete(archive)
    assert out.lcl_hash == mgr.lcl_hash
    assert fails["n"] == 2


def test_permanent_corruption_fails_cleanly(archive2cp, monkeypatch):
    archive, mgr = archive2cp
    orig = FileHistoryArchive.get_xdr_file

    def broken(self, path):
        if "ledger" in path and "0000007f" in path:
            return None
        return orig(self, path)

    monkeypatch.setattr(FileHistoryArchive, "get_xdr_file", broken)
    cm = CatchupManager(NID, PASSPHRASE)
    with pytest.raises(CatchupError):
        cm.catchup_complete(archive)


def test_partial_target_inside_checkpoint(archive2cp):
    archive, mgr = archive2cp
    cm = CatchupManager(NID, PASSPHRASE)
    out = cm.catchup_complete(archive, to_ledger=70)
    assert out.last_closed_ledger_seq == 70
