"""Property-based / fuzz tests (hypothesis).

Reference test model: src/test/FuzzerImpl (decoder fuzz: arbitrary bytes
must never crash, only reject) and the reference's rounding-direction
guarantees in OfferExchange (ExchangeTests property assertions).
"""

from fractions import Fraction

import pytest

pytest.importorskip("hypothesis")  # degrade to a skip, not a collect error

from hypothesis import given, settings, strategies as st  # noqa: E402

from stellar_core_tpu import xdr as X
from stellar_core_tpu.crypto.keys import PublicKey, SecretKey
from stellar_core_tpu.transactions.offer_exchange import (
    ROUND_NORMAL, ROUND_PATH_STRICT_SEND, adjust_offer, exchange_v10)

INT64_MAX = 2**63 - 1


# ---------------------------------------------------------------------------
# decoder fuzz: arbitrary bytes never crash, only XdrError

@settings(max_examples=300, deadline=None)
@given(st.binary(max_size=512))
def test_xdr_decoders_never_crash(data):
    for cls in (X.TransactionEnvelope, X.LedgerEntry, X.LedgerKey,
                X.SCPEnvelope, X.StellarMessage, X.LedgerHeader,
                X.AuthenticatedMessage):
        try:
            cls.from_xdr(data)
        except X.XdrError:
            pass  # rejection is the only acceptable failure


@settings(max_examples=200, deadline=None)
@given(st.binary(min_size=32, max_size=32), st.integers(0, 6))
def test_xdr_bitflip_roundtrip_stability(seed, flip_byte):
    """Encode a valid envelope, flip a byte, decode: either rejects or
    yields a value that re-encodes deterministically (no crash, no
    round-trip instability)."""
    sk = SecretKey(seed)
    env = X.TransactionEnvelope.v1(X.TransactionV1Envelope(
        tx=X.Transaction(
            sourceAccount=X.MuxedAccount.ed25519(sk.public_key.ed25519),
            fee=100, seqNum=7, cond=X.Preconditions.none(),
            memo=X.Memo.none(), operations=[]),
        signatures=[]))
    raw = bytearray(env.to_xdr())
    raw[flip_byte % len(raw)] ^= 0xFF
    try:
        decoded = X.TransactionEnvelope.from_xdr(bytes(raw))
    except X.XdrError:
        return
    assert X.TransactionEnvelope.from_xdr(decoded.to_xdr()) == decoded


# ---------------------------------------------------------------------------
# strkey properties

@settings(max_examples=200, deadline=None)
@given(st.binary(min_size=32, max_size=32))
def test_strkey_roundtrip(raw):
    s = PublicKey(raw).to_strkey()
    assert PublicKey.from_strkey(s).ed25519 == raw


@settings(max_examples=200, deadline=None)
@given(st.binary(min_size=32, max_size=32), st.integers(0, 55))
def test_strkey_single_char_corruption_rejected(raw, pos):
    s = PublicKey(raw).to_strkey()
    alphabet = "ABCDEFGHIJKLMNOPQRSTUVWXYZ234567"
    c = s[pos % len(s)]
    repl = alphabet[(alphabet.index(c) + 1) % 32] if c in alphabet else "A"
    corrupted = s[:pos % len(s)] + repl + s[pos % len(s) + 1:]
    if corrupted == s:
        return
    try:
        got = PublicKey.from_strkey(corrupted)
        # CRC16 catches all single-symbol corruptions of the payload
        assert False, f"corrupted strkey accepted: {corrupted}"
    except ValueError:
        pass


# ---------------------------------------------------------------------------
# exchangeV10 rounding-direction properties (consensus-critical)

amounts = st.integers(0, 10**15)
prices = st.integers(1, 10**7)


@settings(max_examples=500, deadline=None)
@given(amounts, amounts, amounts, amounts, prices, prices)
def test_exchange_v10_invariants(mws, mwr, mss, msr, pn, pd):
    p = X.Price(n=pn, d=pd)
    r = exchange_v10(p, mws, mwr, mss, msr, ROUND_NORMAL)
    # caps respected
    assert 0 <= r.num_wheat_received <= min(mws, mwr)
    assert 0 <= r.num_sheep_send <= mss
    # rounding always favors the resting offer: realized price >= offer
    # price (taker never underpays), unless the exchange was cancelled
    if r.num_wheat_received > 0:
        assert Fraction(r.num_sheep_send, r.num_wheat_received) \
            >= Fraction(pn, pd)
    # no taking sheep for zero wheat
    if r.num_wheat_received == 0:
        assert r.num_sheep_send == 0


@settings(max_examples=300, deadline=None)
@given(amounts, amounts, prices, prices)
def test_exchange_strict_send_sends_exactly(mws, mss, pn, pd):
    p = X.Price(n=pn, d=pd)
    r = exchange_v10(p, mws, INT64_MAX, mss, INT64_MAX,
                     ROUND_PATH_STRICT_SEND)
    if r.wheat_stays and r.num_wheat_received > 0:
        assert r.num_sheep_send == mss


@settings(max_examples=300, deadline=None)
@given(amounts, amounts, prices, prices)
def test_adjust_offer_idempotent(amount, cap, pn, pd):
    """adjustOffer(adjustOffer(x)) == adjustOffer(x) (the reference relies
    on this: adjusted offers rest on the book unmodified)."""
    p = X.Price(n=pn, d=pd)
    once = adjust_offer(p, amount, cap)
    assert adjust_offer(p, once, cap) == once
