"""corelint suite tests: every checker proven to fire AND to stay quiet,
suppression round-trip, the whole-tree clean gate, the baseline ratchet,
and the runtime lock-order tracer.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from stellar_core_tpu.lint import (all_rules, check_baseline, load_baseline,
                                   run_paths, rules_by_id, write_baseline)
from stellar_core_tpu.util import lockorder

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_src(tmp_path, relpath, src, rule_ids=None):
    """Write `src` at tmp_path/relpath and lint it in isolation."""
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    rules = rules_by_id(rule_ids) if rule_ids else all_rules()
    return run_paths([str(tmp_path)], rules, root=str(tmp_path))


def rule_hits(report, rule_id):
    return [v for v in report.violations if v.rule == rule_id]


# ---------------------------------------------------------------------------
# clock-discipline
# ---------------------------------------------------------------------------

class TestClockDiscipline:
    def test_fires_on_time_time(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import time
            def f():
                return time.time()
            """, ["clock-discipline"])
        assert len(rule_hits(rep, "clock-discipline")) == 1

    def test_fires_on_aliased_monotonic(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import time as _t
            x = _t.monotonic()
            """, ["clock-discipline"])
        assert len(rule_hits(rep, "clock-discipline")) == 1

    def test_fires_on_from_import_and_datetime_now(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            from time import monotonic
            from datetime import datetime
            a = monotonic()
            b = datetime.now()
            """, ["clock-discipline"])
        assert len(rule_hits(rep, "clock-discipline")) == 2

    def test_fires_on_ns_variants(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import time
            a = time.time_ns()
            b = time.monotonic_ns()
            """, ["clock-discipline"])
        assert len(rule_hits(rep, "clock-discipline")) == 2

    def test_quiet_in_allowed_files_and_on_perf_counter(self, tmp_path):
        allowed = """
            import time
            t = time.time()
            """
        rep = lint_src(tmp_path, "stellar_core_tpu/util/clock.py", allowed,
                       ["clock-discipline"])
        assert not rule_hits(rep, "clock-discipline")
        rep = lint_src(tmp_path, "bench.py", allowed, ["clock-discipline"])
        assert not rule_hits(rep, "clock-discipline")
        rep = lint_src(tmp_path, "pkg/mod.py", """
            import time
            t = time.perf_counter()  # durations are fine
            s = self_time()          # unrelated name
            """, ["clock-discipline"])
        assert not rule_hits(rep, "clock-discipline")

    def test_allowlist_robust_to_root_above_repo(self, tmp_path):
        # relpaths carry extra leading segments when --root sits above the
        # repo; the allowlist must still exempt the blessed files
        p = tmp_path / "repo" / "stellar_core_tpu" / "util" / "clock.py"
        p.parent.mkdir(parents=True)
        p.write_text("import time\nt = time.time()\n")
        rep = run_paths([str(tmp_path)], rules_by_id(["clock-discipline"]),
                        root=str(tmp_path))
        assert not rule_hits(rep, "clock-discipline")
        # ...but a mere filename collision is NOT exempt
        q = tmp_path / "repo" / "workbench.py"
        q.write_text("import time\nt = time.time()\n")
        rep = run_paths([str(q)], rules_by_id(["clock-discipline"]),
                        root=str(tmp_path))
        assert len(rule_hits(rep, "clock-discipline")) == 1


# ---------------------------------------------------------------------------
# ledger-txn-paths
# ---------------------------------------------------------------------------

class TestLedgerTxnPaths:
    def test_fires_on_early_return_without_close(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root, bad):
                ltx = LedgerTxn(root)
                if bad:
                    return None     # leaks the open txn
                ltx.commit()
            """, ["ledger-txn-paths"])
        assert len(rule_hits(rep, "ledger-txn-paths")) == 1

    def test_fires_on_fall_off_end(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                ltx = LedgerTxn(root)
                ltx.load_header()
            """, ["ledger-txn-paths"])
        assert len(rule_hits(rep, "ledger-txn-paths")) == 1

    def test_fires_on_branch_missing_close(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root, ok):
                ltx = LedgerTxn(root)
                if ok:
                    ltx.commit()
                else:
                    pass            # this arm leaks
                return 1
            """, ["ledger-txn-paths"])
        assert len(rule_hits(rep, "ledger-txn-paths")) == 1

    def test_quiet_on_all_paths_closed(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root, ok):
                ltx = LedgerTxn(root)
                if ok:
                    ltx.commit()
                    return 1
                ltx.rollback()
                return 0
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_quiet_on_context_manager(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                with LedgerTxn(root) as ltx:
                    ltx.create(1)
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_quiet_on_try_finally(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root, bad):
                ltx = LedgerTxn(root)
                try:
                    if bad:
                        return None   # finally still closes
                    work(ltx)
                finally:
                    ltx.rollback()
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_quiet_on_except_reraise_with_open_guard(self, tmp_path):
        # the transactions/frame.py shape
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                inner = LedgerTxn(root)
                try:
                    if early():
                        inner.rollback()
                        return 2
                    inner.commit()
                    return 1
                except Exception:
                    if inner._open:
                        inner.rollback()
                    raise
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_fires_when_open_guard_body_does_not_close(self, tmp_path):
        # `if x._open:` alone must not silence the rule — only a body
        # that actually closes does
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                ltx = LedgerTxn(root)
                if ltx._open:
                    log.warning("still open")
                return 1
            """, ["ledger-txn-paths"])
        assert len(rule_hits(rep, "ledger-txn-paths")) == 1

    def test_fires_on_local_alias_without_close(self, tmp_path):
        # a plain local rebinding is not an ownership escape
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                ltx = LedgerTxn(root)
                tmp = ltx
                return 1
            """, ["ledger-txn-paths"])
        assert len(rule_hits(rep, "ledger-txn-paths")) == 1

    def test_quiet_on_raise_caught_and_closed_by_handler(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                ltx = LedgerTxn(root)
                try:
                    if bad():
                        raise ValueError("boom")
                    ltx.commit()
                    return 1
                except Exception:
                    ltx.rollback()
                    return None
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_fires_on_raise_past_narrow_handler(self, tmp_path):
        # a typed handler may not match: the raise can still escape open
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                ltx = LedgerTxn(root)
                try:
                    raise KeyError("boom")
                except ValueError:
                    ltx.rollback()
                    return None
                ltx.commit()
                return 1
            """, ["ledger-txn-paths"])
        assert len(rule_hits(rep, "ledger-txn-paths")) == 1

    def test_many_sequential_branches_complete_fast(self, tmp_path):
        # one-bit state must not explode 2^n across sequential ifs
        branches = "\n".join(
            f"    if cond({i}):\n        note({i})" for i in range(60))
        src = ("def f(root):\n"
               "    ltx = LedgerTxn(root)\n"
               f"{branches}\n"
               "    ltx.commit()\n")
        p = tmp_path / "m.py"
        p.write_text(src)
        import time as _t
        t0 = _t.perf_counter()
        rep = run_paths([str(p)], rules_by_id(["ledger-txn-paths"]),
                        root=str(tmp_path))
        assert _t.perf_counter() - t0 < 5.0
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_fires_on_conditionally_evaluated_close(self, tmp_path):
        # a close in a short-circuit / ternary position is not certain
        rep = lint_src(tmp_path, "m.py", """
            def f(root, ok):
                ltx = LedgerTxn(root)
                return ok and ltx.commit()

            def g(root, ok):
                ltx = LedgerTxn(root)
                return ltx.commit() if ok else None
            """, ["ledger-txn-paths"])
        assert len(rule_hits(rep, "ledger-txn-paths")) == 2

    def test_quiet_on_close_in_unconditional_position(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                ltx = LedgerTxn(root)
                return note(ltx.commit())
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_quiet_on_binding_inside_try_with_enclosing_handler(
            self, tmp_path):
        # a raise caught by the ENCLOSING handler is not a function exit
        # for a binding that lives inside the try body
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                try:
                    ltx = LedgerTxn(root)
                    do_work(ltx)
                    raise RetryError()
                except RetryError:
                    ltx.rollback()
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_quiet_on_nested_closure_closer(self, tmp_path):
        # the offer_ops.py use_pool() shape
        rep = lint_src(tmp_path, "m.py", """
            def f(root, alt):
                book = LedgerTxn(root)
                def use_pool():
                    book.rollback()
                    return 2
                if alt:
                    return use_pool()
                book.commit()
                return 1
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_quiet_on_while_true_commit_break(self, tmp_path):
        # `while True` has no zero-iteration path; break carries its state
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                ltx = LedgerTxn(root)
                while True:
                    if step(ltx):
                        ltx.commit()
                        break
                return 1
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")

    def test_fires_on_plain_loop_that_may_not_run(self, tmp_path):
        # a zero-iteration for loop never reaches the commit
        rep = lint_src(tmp_path, "m.py", """
            def f(root, items):
                ltx = LedgerTxn(root)
                for it in items:
                    ltx.commit()
                    break
            """, ["ledger-txn-paths"])
        assert len(rule_hits(rep, "ledger-txn-paths")) == 1

    def test_fires_on_annotated_binding(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root, bad):
                ltx: LedgerTxn = LedgerTxn(root)
                if bad:
                    return None     # leaks the open txn
                ltx.commit()
            """, ["ledger-txn-paths"])
        assert len(rule_hits(rep, "ledger-txn-paths")) == 1

    def test_quiet_on_ownership_escape(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(root):
                ltx = LedgerTxn(root)
                return ltx            # caller owns it now
            """, ["ledger-txn-paths"])
        assert not rule_hits(rep, "ledger-txn-paths")


# ---------------------------------------------------------------------------
# decode-free-seam
# ---------------------------------------------------------------------------

class TestDecodeFreeSeam:
    def test_fires_on_entries_in_merge_raw(self, tmp_path):
        rep = lint_src(tmp_path, "stellar_core_tpu/bucket/bucket.py", """
            def merge_buckets_raw(old, new, keep, proto, store):
                for e in old.entries:     # rehydrates!
                    pass
            """, ["decode-free-seam"])
        assert len(rule_hits(rep, "decode-free-seam")) == 1

    def test_fires_on_bucketentry_in_stream_writer(self, tmp_path):
        rep = lint_src(tmp_path, "stellar_core_tpu/bucket/manager.py", """
            class BucketStreamWriter:
                def write(self, key, rec):
                    be = BucketEntry.liveEntry(rec)   # decoded construction
                    self.out.append(be)
            """, ["decode-free-seam"])
        assert len(rule_hits(rep, "decode-free-seam")) == 1

    def test_fires_anywhere_in_native_bridge(self, tmp_path):
        rep = lint_src(tmp_path, "stellar_core_tpu/ledger/native_apply.py",
                       """
            def export(bucket):
                return bucket.entries
            """, ["decode-free-seam"])
        assert len(rule_hits(rep, "decode-free-seam")) == 1

    def test_quiet_outside_scopes_and_on_raw_use(self, tmp_path):
        # .entries outside the raw scopes is fine
        rep = lint_src(tmp_path, "stellar_core_tpu/bucket/bucket.py", """
            def merge_buckets(old, new):
                return old.entries + new.entries

            def merge_buckets_raw(old, new, keep, proto, store):
                w = store.stream_writer(proto)
                for k, rec in old.iter_raw():
                    w.write(k, rec)
                return w.finalize()
            """, ["decode-free-seam"])
        assert not rule_hits(rep, "decode-free-seam")


# ---------------------------------------------------------------------------
# exception-hygiene
# ---------------------------------------------------------------------------

class TestExceptionHygiene:
    def test_fires_on_silent_swallow(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f():
                try:
                    g()
                except Exception:
                    pass
            """, ["exception-hygiene"])
        assert len(rule_hits(rep, "exception-hygiene")) == 1

    def test_fires_on_bare_except(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f():
                try:
                    g()
                except:
                    return None
            """, ["exception-hygiene"])
        assert len(rule_hits(rep, "exception-hygiene")) == 1

    def test_quiet_on_narrow_log_raise_and_failure_sink(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f():
                try:
                    g()
                except ValueError:
                    pass              # narrow: fine
                try:
                    g()
                except Exception as e:
                    log.warning("boom: %s", e)
                try:
                    g()
                except Exception:
                    raise
                try:
                    g()
                except Exception as e:
                    return self._fail(str(e))
            """, ["exception-hygiene"])
        assert not rule_hits(rep, "exception-hygiene")


# ---------------------------------------------------------------------------
# metric-registry
# ---------------------------------------------------------------------------

class TestMetricRegistry:
    def test_fires_on_malformed_name(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            registry().counter("NotDotted")
            """, ["metric-registry"])
        assert len(rule_hits(rep, "metric-registry")) == 1

    def test_fires_on_non_canonical_name(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            registry().timer("ledger.made.up-name")
            """, ["metric-registry"])
        assert len(rule_hits(rep, "metric-registry")) == 1

    def test_fires_on_unpinned_fstring(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(reg, level):
                reg.counter(f"made-up.{level}")
            """, ["metric-registry"])
        assert len(rule_hits(rep, "metric-registry")) == 1

    def test_quiet_on_canonical_prefix_and_dynamic(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            def f(reg, level, name):
                reg.timer("ledger.ledger.close")
                reg.counter(f"bucketlistdb.probe.level-{level}")
                reg.meter(name)            # dynamic: skipped
                with scoped_timer("bucket.merge.time"):
                    pass
            """, ["metric-registry"])
        assert not rule_hits(rep, "metric-registry")

    def test_fires_on_keyword_name_argument(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            registry().timer(name="totally.bogus.name")
            """, ["metric-registry"])
        assert len(rule_hits(rep, "metric-registry")) == 1

    def test_fires_on_bad_scoped_timer_literal(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            with scoped_timer("not.a.canonical-name"):
                pass
            """, ["metric-registry"])
        assert len(rule_hits(rep, "metric-registry")) == 1

    def test_quiet_on_soroban_canonical_names(self, tmp_path):
        # every metric the Soroban subsystem registers must be canonical
        rep = lint_src(tmp_path, "m.py", """
            def f(reg):
                reg.timer("soroban.host.invoke")
                reg.meter("soroban.host.trap")
                reg.meter("soroban.host.budget-exceeded")
                reg.histogram("soroban.host.cpu-insns")
                reg.meter("soroban.ttl.extend")
                reg.meter("soroban.ttl.restore")
                reg.meter("soroban.ttl.evicted")
                reg.histogram("soroban.apply.clusters")
                with scoped_timer("soroban.apply.phase"):
                    pass
                reg.meter("soroban.transaction.apply")
            """, ["metric-registry"])
        assert not rule_hits(rep, "metric-registry")

    def test_fires_on_unregistered_soroban_name(self, tmp_path):
        # "soroban." is NOT a blanket canonical prefix: new names must be
        # added to CANONICAL_METRICS explicitly
        rep = lint_src(tmp_path, "m.py", """
            registry().meter("soroban.host.made-up")
            """, ["metric-registry"])
        assert len(rule_hits(rep, "metric-registry")) == 1

    def test_quiet_on_telemetry_prefixes(self, tmp_path):
        # ISSUE 20: the historical-telemetry plane mints per-series
        # names under blanket prefixes (timeseries./closecost./anomaly.)
        rep = lint_src(tmp_path, "m.py", """
            def f(reg, name):
                reg.counter("timeseries.capture.ticks")
                reg.timer("timeseries.capture.tick-time")
                reg.gauge("closecost.records.retained")
                reg.counter("anomaly.flags")
                reg.gauge(f"anomaly.active.{name}")
            """, ["metric-registry"])
        assert not rule_hits(rep, "metric-registry")

    def test_fires_on_near_miss_telemetry_names(self, tmp_path):
        # prefix matching is exact: sibling spellings stay undocumented
        rep = lint_src(tmp_path, "m.py", """
            def f(reg):
                reg.counter("timeserieses.capture.ticks")
                reg.gauge("closecosts.records.retained")
                reg.counter("anomalies.active.total")
            """, ["metric-registry"])
        assert len(rule_hits(rep, "metric-registry")) == 3


# ---------------------------------------------------------------------------
# eventlog-partitions
# ---------------------------------------------------------------------------

class TestEventlogPartitions:
    def test_fires_on_unknown_partition_literal(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            from stellar_core_tpu.util import eventlog
            def f():
                eventlog.record("Ledgerz", "INFO", "typo'd partition")
            """, ["eventlog-partitions"])
        assert len(rule_hits(rep, "eventlog-partitions")) == 1

    def test_fires_on_bare_imported_record(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            from stellar_core_tpu.util.eventlog import record
            def f():
                record("NotAPartition", "WARNING", "x", k=1)
            """, ["eventlog-partitions"])
        assert len(rule_hits(rep, "eventlog-partitions")) == 1

    def test_quiet_on_known_partitions_and_dynamic(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            from stellar_core_tpu.util import eventlog
            def f(part):
                eventlog.record("Ledger", "INFO", "close sealed", seq=1)
                eventlog.record("Overlay", "WARNING", "peer dropped")
                eventlog.record(part, "INFO", "dynamic: runtime checks")
            """, ["eventlog-partitions"])
        assert not rule_hits(rep, "eventlog-partitions")

    def test_quiet_on_unrelated_record_methods(self, tmp_path):
        # TraceBuffer.record(span) and friends must not be mistaken for
        # the flight recorder
        rep = lint_src(tmp_path, "m.py", """
            def f(buf, root, rec):
                buf.record(root)
                rec.record("whatever string")
            """, ["eventlog-partitions"])
        assert not rule_hits(rep, "eventlog-partitions")


# ---------------------------------------------------------------------------
# lock-order (static)
# ---------------------------------------------------------------------------

class TestLockOrderStatic:
    def test_fires_on_lexical_abba(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            class A:
                def one(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
                def two(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """, ["lock-order"])
        assert rule_hits(rep, "lock-order")

    def test_fires_on_call_graph_cycle(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            class A:
                def outer(self):
                    with self._a_lock:
                        self.helper()
                def helper(self):
                    with self._b_lock:
                        pass
                def inverted(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """, ["lock-order"])
        assert rule_hits(rep, "lock-order")

    def test_fires_on_multi_item_with_abba(self, tmp_path):
        # `with a, b:` orders a before b — inverting it is still a cycle
        rep = lint_src(tmp_path, "m.py", """
            class A:
                def one(self):
                    with self._a_lock, self._b_lock:
                        pass
                def two(self):
                    with self._b_lock, self._a_lock:
                        pass
            """, ["lock-order"])
        assert rule_hits(rep, "lock-order")

    def test_fires_on_cross_object_abba_with_type_evidence(self, tmp_path):
        # self._lock vs other._lock resolved via annotation / constructor
        rep = lint_src(tmp_path, "m.py", """
            class Snap:
                def grab(self, store: "Store"):
                    pass

            class Store:
                def one(self, snap: Snap):
                    with self._lock:
                        with snap._lock:
                            pass

            class Snap2(Snap):
                pass

            def two(store_arg):
                store = Store()
                snap = Snap()
                with snap._lock:
                    with store._lock:
                        pass
            """, ["lock-order"])
        assert rule_hits(rep, "lock-order")

    def test_unresolvable_receiver_is_not_collapsed(self, tmp_path):
        # an unknown receiver must be its own node: no self-edge dropping
        # (missed cycles) and no merging with the enclosing class (false
        # cycles)
        rep = lint_src(tmp_path, "m.py", """
            class Store:
                def one(self, snap):
                    with self._lock:
                        with snap._lock:
                            pass
                def two(self, snap):
                    with self._lock:
                        with snap._lock:
                            pass
            """, ["lock-order"])
        assert not rule_hits(rep, "lock-order")

    def test_same_named_unknowns_do_not_merge_across_functions(
            self, tmp_path):
        # unrelated objects sharing a parameter name must not fabricate a
        # cross-function cycle...
        rep = lint_src(tmp_path, "m.py", """
            class Store:
                def one(self, snap):
                    with self._lock:
                        with snap._lock:
                            pass
                def two(self, snap):
                    with snap._lock:
                        with self._lock:
                            pass
            """, ["lock-order"])
        assert not rule_hits(rep, "lock-order")
        # ...but within ONE function the same name is one object and an
        # inversion is still caught
        rep = lint_src(tmp_path, "m.py", """
            class Store:
                def one(self, snap):
                    with self._lock:
                        with snap._lock:
                            pass
                    with snap._lock:
                        with self._lock:
                            pass
            """, ["lock-order"])
        assert rule_hits(rep, "lock-order")

    def test_no_lock_self_method_does_not_alias_module_function(
            self, tmp_path):
        # a lock-free self.close() must not inherit a same-named module
        # function's acquisitions (would fabricate edges / false cycles)
        rep = lint_src(tmp_path, "m.py", """
            def close():
                with _b_lock:
                    pass

            class A:
                def close(self):
                    pass
                def one(self):
                    with self._a_lock:
                        self.close()

            class B:
                def two(self):
                    with _b_lock:
                        with other_a._a_lock:
                            pass
            """, ["lock-order"])
        assert not rule_hits(rep, "lock-order")

    def test_unknown_self_attr_receivers_do_not_merge_across_classes(
            self, tmp_path):
        # untyped `self.cb._lock` in two unrelated classes must not be
        # one node (would chain their edges into phantom cycles)
        rep = lint_src(tmp_path, "m.py", """
            class A:
                def one(self):
                    with self._lock:
                        with self.cb._lock:
                            pass
            class B:
                def two(self):
                    with self.cb._lock:
                        with self._lock:
                            pass
            """, ["lock-order"])
        assert not rule_hits(rep, "lock-order")

    def test_lambda_body_is_not_held_context(self, tmp_path):
        # a deferred lambda runs lock-free: no held-call edge
        rep = lint_src(tmp_path, "m.py", """
            class A:
                def helper(self):
                    with self._b_lock:
                        pass
                def one(self):
                    with self._a_lock:
                        self.defer(lambda: self.helper())
                def two(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """, ["lock-order"])
        assert not rule_hits(rep, "lock-order")

    def test_clock_and_block_are_not_locks(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            class A:
                def one(self):
                    with self.clock:
                        with self._a_lock:
                            pass
                def two(self):
                    with self._a_lock:
                        with self.block:
                            pass
            """, ["lock-order"])
        assert not rule_hits(rep, "lock-order")

    def test_quiet_on_consistent_order(self, tmp_path):
        rep = lint_src(tmp_path, "m.py", """
            class A:
                def one(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
                def two(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
                def three(self):
                    with self._b_lock:
                        pass
            """, ["lock-order"])
        assert not rule_hits(rep, "lock-order")


# ---------------------------------------------------------------------------
# suppressions + baseline ratchet
# ---------------------------------------------------------------------------

class TestSuppressions:
    SRC_BAD = """
        import time
        t = time.time()
        """
    SRC_SUPPRESSED = """
        import time
        t = time.time()  # corelint: disable=clock-discipline -- test fixture
        """

    def test_round_trip(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/m.py", self.SRC_SUPPRESSED,
                       ["clock-discipline"])
        assert not rep.violations
        assert len(rep.suppressed) == 1
        # deleting the suppression comment re-surfaces the violation
        rep = lint_src(tmp_path, "pkg/m.py", self.SRC_BAD,
                       ["clock-discipline"])
        assert len(rep.violations) == 1
        assert not rep.suppressed

    def test_file_level_suppression(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/m.py", """
            # corelint: disable-file=clock-discipline -- fixture module
            import time
            a = time.time()
            b = time.monotonic()
            """, ["clock-discipline"])
        assert not rep.violations
        assert len(rep.suppressed) == 2

    def test_baseline_ratchet_blocks_new_suppressions(self, tmp_path):
        rep = lint_src(tmp_path, "pkg/m.py", self.SRC_SUPPRESSED,
                       ["clock-discipline"])
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), rep)
        assert check_baseline(rep, load_baseline(str(bl))) == []
        # a second suppression in the same file exceeds the ratchet
        rep2 = lint_src(tmp_path, "pkg/m.py", """
            import time
            t = time.time()       # corelint: disable=clock-discipline -- one
            u = time.monotonic()  # corelint: disable=clock-discipline -- two
            """, ["clock-discipline"])
        assert len(rep2.suppressed) == 2
        problems = check_baseline(rep2, load_baseline(str(bl)))
        assert problems and "ratchet" in problems[0]

    def test_baseline_ratchet_flags_stale_entries(self, tmp_path):
        # a removed suppression must not leave headroom for a later
        # unreviewed one: shrinkage demands a baseline regen too
        rep = lint_src(tmp_path, "pkg/m.py", self.SRC_SUPPRESSED,
                       ["clock-discipline"])
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl), rep)
        rep2 = lint_src(tmp_path, "pkg/m.py", "x = 1\n",
                        ["clock-discipline"])
        problems = check_baseline(rep2, load_baseline(str(bl)))
        assert problems and "ratchet down" in problems[0]


# ---------------------------------------------------------------------------
# whole-tree gate + CLI
# ---------------------------------------------------------------------------

class TestWholeTree:
    def test_tree_is_clean_and_matches_baseline(self):
        targets = [os.path.join(REPO_ROOT, "stellar_core_tpu"),
                   os.path.join(REPO_ROOT, "bench.py"),
                   os.path.join(REPO_ROOT, "native")]
        rep = run_paths(targets, all_rules(), root=REPO_ROOT)
        assert rep.files_scanned > 100
        assert rep.violations == [], \
            "\n".join(v.format() for v in rep.violations)
        assert not rep.parse_errors
        baseline = load_baseline(os.path.join(REPO_ROOT,
                                              "LINT_BASELINE.json"))
        assert check_baseline(rep, baseline) == []
        # the documented grandfathered suppressions exist and are listed
        assert rep.suppression_counts() == baseline["suppressions"]

    def test_overlapping_paths_lint_each_file_once(self):
        targets = [os.path.join(REPO_ROOT, "stellar_core_tpu"),
                   os.path.join(REPO_ROOT, "stellar_core_tpu", "util",
                                "metrics.py")]
        rep = run_paths(targets, all_rules(), root=REPO_ROOT)
        key = "stellar_core_tpu/util/metrics.py:exception-hygiene"
        assert rep.suppression_counts()[key] == 1

    def test_cli_rejects_baseline_with_partial_scope(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu.lint",
             "--rules", "clock-discipline",
             "--baseline", "LINT_BASELINE.json"],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert r.returncode == 2
        assert "full scope" in r.stderr
        # a non-cwd --root would mis-key every suppression: rejected too
        r = subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu.lint",
             "--root", str(tmp_path),
             "--baseline", "LINT_BASELINE.json"],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert r.returncode == 2
        assert "full scope" in r.stderr

    def test_nul_byte_file_is_a_parse_error_not_a_crash(self, tmp_path):
        p = tmp_path / "nul.py"
        p.write_bytes(b"x = 1\x00")
        rep = run_paths([str(p)], all_rules(), root=str(tmp_path))
        assert rep.files_scanned == 0
        assert rep.parse_errors and "nul.py" in rep.parse_errors[0]

    def test_cli_rejects_nonexistent_path(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu.lint",
             str(tmp_path / "no_such_dir")],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert r.returncode == 2
        assert "no such path" in r.stderr

    def test_cli_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import time\nx = time.time()\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu.lint", str(bad),
             "--root", str(tmp_path), "--json"],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["counts"] == {"clock-discipline": 1}
        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        r = subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu.lint", str(good),
             "--root", str(tmp_path)],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_cli_list_rules_names_all_six(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "stellar_core_tpu.lint", "--list-rules"],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert r.returncode == 0
        for rule in ("clock-discipline", "ledger-txn-paths",
                     "decode-free-seam", "exception-hygiene",
                     "metric-registry", "lock-order",
                     # native-C pass (ISSUE 15)
                     "reader-discipline", "memcpy-provenance",
                     "unchecked-alloc", "handler-result-discipline",
                     "overlay-pairing"):
            assert rule in r.stdout


# ---------------------------------------------------------------------------
# runtime lock-order tracer
# ---------------------------------------------------------------------------

class TestRuntimeLockTracer:
    @pytest.fixture(autouse=True)
    def _traced(self):
        lockorder.enable()
        lockorder.reset_observed()
        yield
        lockorder.disable()
        lockorder.reset_observed()

    def test_disabled_factory_returns_plain_lock(self):
        lockorder.disable()
        lk = lockorder.make_lock("x")
        assert type(lk).__name__ in ("lock", "LockType")

    def test_records_acquisition_dag(self):
        a = lockorder.make_lock("a")
        b = lockorder.make_lock("b")
        with a:
            with b:
                pass
        assert lockorder.observed_edges() == {"a": {"b"}}

    def test_fail_stops_on_inversion(self):
        a = lockorder.make_lock("a")
        b = lockorder.make_lock("b")
        with a:
            with b:
                pass
        with pytest.raises(lockorder.LockOrderError):
            with b:
                with a:
                    pass

    def test_transitive_inversion_detected(self):
        a = lockorder.make_lock("a")
        b = lockorder.make_lock("b")
        c = lockorder.make_lock("c")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(lockorder.LockOrderError):
            with c:
                with a:
                    pass

    def test_rlock_reentry_is_not_an_inversion(self):
        r = lockorder.make_rlock("r")
        with r:
            with r:
                pass
        assert lockorder.observed_edges() == {}

    def test_traced_lock_api_parity(self):
        import threading
        lk = lockorder.make_lock("parity")
        with lk:
            assert lk.locked()
        # a traced RLock exposes exactly the wrapped RLock's surface:
        # .locked() exists only where threading.RLock has it (3.14+)
        r = lockorder.make_rlock("parity-r")
        if hasattr(threading.RLock(), "locked"):
            r.locked()
        else:
            with pytest.raises(AttributeError):
                r.locked()

    def test_same_class_two_instances_consistent(self):
        # two instances of the same lock class are one DAG node
        h1 = lockorder.make_lock("metrics.histogram")
        reg = lockorder.make_lock("metrics.registry")
        with reg:
            with h1:
                pass
        assert lockorder.observed_edges() == \
            {"metrics.registry": {"metrics.histogram"}}
