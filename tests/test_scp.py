"""SCP library tests (reference model: src/scp/test/SCPTests.cpp).

Covers quorum-slice / v-blocking math, transitive quorum discovery, and full
multi-node consensus rounds (nomination → prepare → confirm → externalize)
over an in-memory envelope bus with deterministic timers.
"""

import hashlib

import pytest

from stellar_core_tpu import scp as S
from stellar_core_tpu.scp.driver import SCPDriver, ValidationLevel
from stellar_core_tpu.scp.quorum import _compiled_slice_ok, compile_qset
from stellar_core_tpu.xdr import scp as SX
from stellar_core_tpu.xdr import types as XT


def nid(i: int) -> bytes:
    return hashlib.sha256(b"node%d" % i).digest()


def make_qset(node_ids, threshold, inner=()):
    return SX.SCPQuorumSet(
        threshold=threshold,
        validators=[XT.node_id(n) for n in node_ids],
        innerSets=list(inner))


class TestQuorumMath:
    def test_quorum_slice_threshold(self):
        q = make_qset([nid(0), nid(1), nid(2), nid(3)], 3)
        assert S.is_quorum_slice(q, {nid(0), nid(1), nid(2)})
        assert not S.is_quorum_slice(q, {nid(0), nid(1)})
        assert S.is_quorum_slice(q, {nid(0), nid(1), nid(2), nid(3)})

    def test_v_blocking(self):
        # threshold 3 of 4 → any 2 nodes are v-blocking (4-3+1=2)
        q = make_qset([nid(0), nid(1), nid(2), nid(3)], 3)
        assert S.is_v_blocking(q, {nid(0), nid(1)})
        assert not S.is_v_blocking(q, {nid(0)})
        assert not S.is_v_blocking(q, {nid(9)})

    def test_v_blocking_zero_threshold(self):
        q = make_qset([nid(0)], 0)
        assert not S.is_v_blocking(q, {nid(0)})

    def test_compiled_slice_matches_is_quorum_slice(self):
        inner = make_qset([nid(4), nid(5), nid(6)], 2)
        q = make_qset([nid(0), nid(1), nid(2)], 2, inner=[inner])
        cq = compile_qset(q)
        for nodes in ({nid(0), nid(1)}, {nid(0)}, {nid(0), nid(4), nid(5)},
                      {nid(4), nid(5)}, set(),
                      {nid(0), nid(1), nid(2), nid(4), nid(5), nid(6)}):
            assert _compiled_slice_ok(cq, nodes) \
                == S.is_quorum_slice(q, nodes)

    def test_compiled_slice_zero_threshold(self):
        # is_quorum_slice returns count >= 0 == True unconditionally for
        # a threshold-0 set; the compiled walker must agree even when no
        # member matches (is_qset_sane never vets locally-built sets)
        q = make_qset([nid(0)], 0)
        assert _compiled_slice_ok(compile_qset(q), set())
        assert _compiled_slice_ok(compile_qset(q), {nid(9)})
        assert S.is_quorum_slice(q, set())

    def test_nested_qset(self):
        innerA = make_qset([nid(1), nid(2), nid(3)], 2)
        innerB = make_qset([nid(4), nid(5), nid(6)], 2)
        q = make_qset([nid(0)], 2, inner=[innerA, innerB])
        # slice needs node0 + one inner, or both inners
        assert S.is_quorum_slice(q, {nid(0), nid(1), nid(2)})
        assert S.is_quorum_slice(q, {nid(1), nid(2), nid(4), nid(5)})
        assert not S.is_quorum_slice(q, {nid(0), nid(1)})
        # blocking: 2 of 3 groups must be hit
        assert S.is_v_blocking(q, {nid(0), nid(2), nid(3)})
        assert not S.is_v_blocking(q, {nid(2), nid(4)} - {nid(4)})

    def test_qset_sane(self):
        assert S.is_qset_sane(make_qset([nid(0), nid(1), nid(2)], 2))
        assert not S.is_qset_sane(make_qset([], 0))
        assert not S.is_qset_sane(make_qset([nid(0)], 2))
        dup = make_qset([nid(0), nid(0)], 1)
        assert not S.is_qset_sane(dup)

    def test_normalize(self):
        triv = make_qset([nid(5)], 1)
        q = make_qset([nid(0)], 2, inner=[triv])
        n = S.normalize_qset(q)
        assert len(n.validators) == 2 and not n.innerSets

    def test_is_quorum_transitive(self):
        # nodes 0..3 all use 3-of-4; a statement map where only 0,1,2 voted
        q = make_qset([nid(0), nid(1), nid(2), nid(3)], 3)
        stmts = {nid(i): "st%d" % i for i in range(3)}
        assert S.is_quorum(q, stmts, lambda st: q, lambda st: True)
        stmts2 = {nid(i): "st%d" % i for i in range(2)}
        assert not S.is_quorum(q, stmts2, lambda st: q, lambda st: True)


# ---------------------------------------------------------------------------
# multi-node consensus harness
# ---------------------------------------------------------------------------

class BusDriver(SCPDriver):
    """Test SCPDriver: routes envelopes via a shared bus, shared qset
    registry, manual timers."""

    def __init__(self, bus, node_id):
        self.bus = bus
        self.node_id = node_id
        self.timers = {}          # timer_id -> (fire_at_round, callback)
        self.externalized = {}    # slot -> value
        self.qsets = bus.qsets

    def validate_value(self, slot_index, value, nomination):
        return ValidationLevel.FULLY_VALIDATED

    def combine_candidates(self, slot_index, candidates):
        return max(candidates)

    def get_qset(self, qset_hash):
        return self.qsets.get(qset_hash)

    def emit_envelope(self, envelope):
        self.bus.queue.append((self.node_id, envelope))

    def setup_timer(self, slot_index, timer_id, timeout, callback):
        if callback is None:
            self.timers.pop(timer_id, None)
        else:
            self.timers[timer_id] = callback

    def value_externalized(self, slot_index, value):
        self.externalized[slot_index] = value


class Bus:
    def __init__(self, n_nodes, threshold=None):
        self.qsets = {}
        self.queue = []
        ids = [nid(i) for i in range(n_nodes)]
        threshold = threshold or (n_nodes - 1)
        qset = make_qset(ids, threshold)
        self.qsets[S.qset_hash(qset)] = qset
        self.nodes = {}
        for i in ids:
            d = BusDriver(self, i)
            self.nodes[i] = S.SCP(d, i, True, qset)

    def drain(self, max_msgs=50000):
        n = 0
        while self.queue and n < max_msgs:
            sender, env = self.queue.pop(0)
            for i, node in self.nodes.items():
                if i != sender:
                    node.receive_envelope(env)
            n += 1
        assert n < max_msgs, "message storm"

    def fire_timers(self):
        fired = False
        for node in self.nodes.values():
            timers, node.driver.timers = dict(node.driver.timers), {}
            for cb in timers.values():
                cb()
                fired = True
        return fired

    def run_to_consensus(self, slot, max_rounds=10):
        for _ in range(max_rounds):
            self.drain()
            if all(node.driver.externalized.get(slot) is not None
                   for node in self.nodes.values()):
                return
            self.fire_timers()
        self.drain()

    def externalized_values(self, slot):
        return [node.driver.externalized.get(slot)
                for node in self.nodes.values()]


@pytest.mark.parametrize("n,threshold", [(4, 3), (5, 4), (3, 2)])
def test_consensus_all_nominate(n, threshold):
    bus = Bus(n, threshold)
    slot = 1
    for i, node in bus.nodes.items():
        node.nominate(slot, b"value-from-%s" % i[:4].hex().encode(), b"prev")
    bus.run_to_consensus(slot)
    vals = bus.externalized_values(slot)
    assert all(v is not None for v in vals), f"not all externalized: {vals}"
    assert len(set(vals)) == 1, "diverged!"


def test_consensus_single_nominator():
    """Only one node nominates; timers drive the rest to adopt."""
    bus = Bus(4, 3)
    slot = 7
    first = next(iter(bus.nodes))
    bus.nodes[first].nominate(slot, b"lonely-value", b"prev")
    # others must still start nomination (herder triggers every validator)
    for i, node in bus.nodes.items():
        if i != first:
            node.nominate(slot, b"value-%s" % i[:2].hex().encode(), b"prev")
    bus.run_to_consensus(slot)
    vals = bus.externalized_values(slot)
    assert all(v is not None for v in vals)
    assert len(set(vals)) == 1


def test_consensus_successive_slots():
    bus = Bus(4, 3)
    for slot in (1, 2, 3):
        for i, node in bus.nodes.items():
            node.nominate(slot, b"slot%d-%s" % (slot, i[:2].hex().encode()),
                          b"prev%d" % slot)
        bus.run_to_consensus(slot)
        vals = bus.externalized_values(slot)
        assert all(v is not None for v in vals) and len(set(vals)) == 1


def test_externalize_message_carries_commit():
    bus = Bus(3, 2)
    for i, node in bus.nodes.items():
        node.nominate(1, b"v", b"p")
    bus.run_to_consensus(1)
    node = next(iter(bus.nodes.values()))
    env = node.get_latest_messages_send(1)
    types = [e.statement.pledges.type for e in env]
    assert SX.SCPStatementType.SCP_ST_EXTERNALIZE in types


def test_purge_slots():
    bus = Bus(3, 2)
    for slot in (1, 2, 3):
        for node in bus.nodes.values():
            node.nominate(slot, b"v%d" % slot, b"p")
        bus.run_to_consensus(slot)
    node = next(iter(bus.nodes.values()))
    assert node.get_high_slot_index() == 3
    node.purge_slots(3)
    assert 1 not in node.slots and 2 not in node.slots and 3 in node.slots


def test_laggard_catches_up_via_vblocking_bump():
    """A node that misses nomination joins the ballot phase via counters."""
    bus = Bus(4, 3)
    slot = 1
    laggard = list(bus.nodes)[-1]
    for i, node in bus.nodes.items():
        if i != laggard:
            node.nominate(slot, b"v-%s" % i[:2].hex().encode(), b"p")
    bus.run_to_consensus(slot)
    vals = bus.externalized_values(slot)
    # 3-of-4 can externalize without the laggard; laggard must still converge
    non_lag = [v for i, v in zip(bus.nodes, vals) if i != laggard]
    assert all(v is not None for v in non_lag)
    assert len(set(non_lag)) == 1
    assert bus.nodes[laggard].driver.externalized.get(slot) in (
        None, non_lag[0])


class TestStatementSanity:
    """Regression tests for BallotProtocol::isStatementSane semantics."""

    def _prepare_st(self, ballot_n=5, nC=0, nH=0, prepared=None,
                    prepared_prime=None):
        from stellar_core_tpu.scp.ballot import BallotProtocol
        val = b"v" * 32
        pr = SX.SCPPrepare(
            quorumSetHash=b"\0" * 32,
            ballot=SX.SCPBallot(counter=ballot_n, value=val),
            prepared=prepared, preparedPrime=prepared_prime, nC=nC, nH=nH)
        st = SX.SCPStatement(nodeID=XT.node_id(nid(0)), slotIndex=1,
                             pledges=SX.SCPStatementPledges.prepare(pr))
        return BallotProtocol._sane(st), st

    def test_nc_above_nh_rejected(self):
        prepared = SX.SCPBallot(counter=5, value=b"v" * 32)
        ok, _ = self._prepare_st(nC=4, nH=2, prepared=prepared)
        assert not ok

    def test_nh_without_prepared_rejected(self):
        ok, _ = self._prepare_st(nH=3, prepared=None)
        assert not ok

    def test_nh_above_prepared_counter_rejected(self):
        prepared = SX.SCPBallot(counter=2, value=b"v" * 32)
        ok, _ = self._prepare_st(nH=3, prepared=prepared)
        assert not ok

    def test_prepared_prime_must_be_less_incompatible(self):
        prepared = SX.SCPBallot(counter=4, value=b"v" * 32)
        pp_bad = SX.SCPBallot(counter=3, value=b"v" * 32)  # compatible: bad
        ok, _ = self._prepare_st(prepared=prepared, prepared_prime=pp_bad)
        assert not ok
        pp_good = SX.SCPBallot(counter=3, value=b"w" * 32)
        ok, _ = self._prepare_st(prepared=prepared, prepared_prime=pp_good)
        assert ok

    def test_zero_counter_rejected_unless_self(self):
        from stellar_core_tpu.scp.ballot import BallotProtocol
        _, st = self._prepare_st(ballot_n=0)
        assert not BallotProtocol._sane(st)
        assert BallotProtocol._sane(st, self_st=True)


class TestHeardFromQuorumCache:
    """The incremental per-slot quorum state (quorum.StatementIndex,
    reference: Slot::mHeardFromQuorum) must answer EXACTLY what a
    from-scratch is_quorum walk over the raw statements answers — across
    ballot bumps, qset changes mid-slot, counter regressions and the
    threshold-0 edge."""

    @staticmethod
    def _raw_counter(st):
        pl = st.pledges
        if pl.type == SX.SCPStatementType.SCP_ST_PREPARE:
            return pl.prepare.ballot.counter
        if pl.type == SX.SCPStatementType.SCP_ST_CONFIRM:
            return pl.confirm.ballot.counter
        return 2**31 - 1

    def _scratch(self, slot):
        """The pre-cache implementation: full is_quorum over the raw
        latest envelopes with per-call qset resolution."""
        from stellar_core_tpu.scp import quorum as Q
        bp = slot.ballot
        if bp.b is None:
            return None
        stmts = {n: e.statement for n, e in bp.latest_envelopes.items()}
        return Q.is_quorum(slot.local_node.qset, stmts,
                           slot.qset_of_statement,
                           lambda st: self._raw_counter(st) >= bp.b[0])

    def _cached(self, slot):
        from stellar_core_tpu.scp import quorum as Q
        bp = slot.ballot
        if bp.b is None:
            return None
        ln = slot.local_node
        return Q.heard_from_quorum(ln.qset, ln.qset_hash, bp.index, bp.b[0])

    def _prep_env(self, i, counter, value, qset_hash, slot=1):
        pr = SX.SCPPrepare(quorumSetHash=qset_hash,
                           ballot=SX.SCPBallot(counter=counter, value=value),
                           prepared=None, preparedPrime=None, nC=0, nH=0)
        st = SX.SCPStatement(nodeID=XT.node_id(nid(i)), slotIndex=slot,
                             pledges=SX.SCPStatementPledges.prepare(pr))
        return SX.SCPEnvelope(statement=st, signature=b"\0" * 64)

    def _make_slot(self, threshold=3, n=4):
        bus = Bus(n, threshold)
        node = bus.nodes[nid(0)]
        slot = node.get_slot(1)
        slot.bump_state(b"v" * 32, force=True)   # sets b=(1, v)
        return bus, slot

    def test_cached_matches_scratch_as_quorum_forms(self):
        bus, slot = self._make_slot()
        qh = S.qset_hash(next(iter(bus.qsets.values())))
        val = b"v" * 32
        # statements arrive one by one; the verdict must track scratch at
        # every step, through the False -> True transition
        for i in (1, 2, 3):
            assert self._cached(slot) == self._scratch(slot)
            slot.process_envelope(self._prep_env(i, 1, val, qh))
        assert self._cached(slot) is True
        assert self._cached(slot) == self._scratch(slot)
        assert slot.ballot.heard_from_quorum

    def test_cached_matches_scratch_across_ballot_bumps(self):
        bus, slot = self._make_slot()
        qh = S.qset_hash(next(iter(bus.qsets.values())))
        val = b"v" * 32
        for i in (1, 2, 3):
            slot.process_envelope(self._prep_env(i, 1, val, qh))
        assert self._cached(slot) is True
        # peers move to counter 3: heard at the OLD counter stays true
        # (monotone latch), heard at the new counter must re-evaluate —
        # and the protocol's own bump (v-blocking ahead) resets the edge
        for i in (1, 2):
            slot.process_envelope(self._prep_env(i, 3, val, qh))
            assert self._cached(slot) == self._scratch(slot)
        slot.process_envelope(self._prep_env(3, 3, val, qh))
        assert slot.ballot.b[0] >= 3   # _attempt_bump chased the fleet
        assert self._cached(slot) == self._scratch(slot) == True  # noqa: E712

    def test_qset_change_mid_slot_invalidates_latch(self):
        # local qset = unanimous 4-of-4: losing ONE member's slice breaks
        # the quorum, so a mid-slot qset change must flip the verdict
        bus, slot = self._make_slot(threshold=4)
        qh = S.qset_hash(next(iter(bus.qsets.values())))
        val = b"v" * 32
        for i in (1, 2, 3):
            slot.process_envelope(self._prep_env(i, 1, val, qh))
        assert self._cached(slot) == self._scratch(slot) == True  # noqa: E712
        # node 3 re-announces under a foreign qset nobody here satisfies;
        # its newer statement (same counter, higher value) replaces the
        # old one and the latched True MUST be dropped, not served stale
        foreign = make_qset([nid(9)], 1)
        bus.qsets[S.qset_hash(foreign)] = foreign
        slot.process_envelope(
            self._prep_env(3, 1, b"w" * 32, S.qset_hash(foreign)))
        assert self._scratch(slot) is False
        assert self._cached(slot) == self._scratch(slot)

    def test_threshold_zero_edge(self):
        # a threshold-0 local qset is trivially satisfied (PR 6 review
        # edge: the compiled walker must agree with is_quorum_slice) —
        # heard-from-quorum must answer True even with zero voters
        from stellar_core_tpu.scp import quorum as Q
        q0 = make_qset([nid(7)], 0)
        idx = Q.StatementIndex()
        assert Q.heard_from_quorum(q0, S.qset_hash(q0), idx, 1) is True
        stmts = {}
        assert Q.is_quorum(q0, stmts, lambda st: None, lambda st: True)

    def test_statement_index_counter_regression_drops_latch(self):
        """A node whose newer statement carries a LOWER counter (legal
        across a PREPARE->CONFIRM phase edge; trivial for a Byzantine
        orderer) must invalidate monotone latches — the voted set can
        shrink, so a latched True is no longer safe to serve."""
        from stellar_core_tpu.scp import quorum as Q
        q = make_qset([nid(1)], 1)
        cq_holder = make_qset([nid(1)], 1)
        idx = Q.StatementIndex()
        idx.note_statement(nid(1), 5, cq_holder, b"h1")
        assert Q.heard_from_quorum(q, b"local", idx, 5) is True
        assert idx.lookup(("hfq", 5, b"local")) is True   # latched
        idx.note_statement(nid(1), 2, cq_holder, b"h1")   # regression
        assert idx.lookup(("hfq", 5, b"local")) is None   # latch dropped
        assert Q.heard_from_quorum(q, b"local", idx, 5) is False


def test_watcher_nominate_returns_false():
    bus = Bus(3)
    qset = next(iter(bus.qsets.values()))
    watcher = S.SCP(BusDriver(bus, nid(0)), nid(0),
                    is_validator=False, qset=qset)
    assert watcher.nominate(1, b"x" * 32, b"p" * 32) is False


def test_normalize_removal_decrements_threshold():
    q = make_qset([nid(0), nid(1)], 2)
    n = S.normalize_qset(q, remove=nid(0))
    assert n.threshold == 1 and len(n.validators) == 1
    # inner set consisting solely of the removed node: auto-satisfied
    q2 = make_qset([nid(1)], 2, inner=[make_qset([nid(0)], 1)])
    n2 = S.normalize_qset(q2, remove=nid(0))
    assert n2.threshold == 1 and len(n2.validators) == 1 and not n2.innerSets


class TestVBlockingFastPaths:
    """Round-12 latching (ROADMAP 4c): the compiled/latched v-blocking
    checks must answer EXACTLY what the from-scratch walks answer —
    differential style, like the heard-from-quorum suite above."""

    def test_compiled_v_blocking_matches_raw_randomized(self):
        import random
        from stellar_core_tpu.scp import quorum as Q
        rng = random.Random(13)
        ids = [nid(i) for i in range(12)]
        for _ in range(200):
            n = 2 + rng.randrange(6)
            members = rng.sample(ids, n)
            inner = []
            if rng.random() < 0.5:
                im = rng.sample(ids, 2 + rng.randrange(3))
                inner = [make_qset(im, 1 + rng.randrange(len(im)))]
            q = make_qset(members, 1 + rng.randrange(n + len(inner)), inner)
            nodes = {i for i in ids if rng.random() < 0.4}
            assert Q.is_v_blocking_compiled(Q.compile_qset_cached(q),
                                            nodes) \
                == Q.is_v_blocking(q, nodes)
        # threshold-0 edge: never v-blocking, both forms
        q0 = make_qset([nid(1)], 0)
        assert Q.is_v_blocking(q0, {nid(1)}) is False
        assert Q.is_v_blocking_compiled(Q.compile_qset_cached(q0),
                                        {nid(1)}) is False

    @staticmethod
    def _scratch_ahead(qset, index, counter):
        """The pre-latch implementation: fresh node-set build + raw
        is_v_blocking walk per call."""
        from stellar_core_tpu.scp import quorum as Q
        nodes = {n for n, c in index.node_counter.items() if c >= counter}
        return Q.is_v_blocking(qset, nodes)

    def test_v_blocking_ahead_latches_and_matches_scratch(self):
        import random
        from stellar_core_tpu.scp import quorum as Q
        rng = random.Random(29)
        q = make_qset([nid(i) for i in range(5)], 3)
        qh = S.qset_hash(q)
        holder = make_qset([nid(9)], 1)
        idx = Q.StatementIndex()
        for step in range(120):
            node = nid(rng.randrange(5))
            counter = 1 + rng.randrange(6)
            idx.note_statement(node, counter, holder, b"h")
            for probe in (1, 2, 3, 4, 5, 6):
                assert Q.v_blocking_ahead(q, qh, idx, probe) \
                    == self._scratch_ahead(q, idx, probe), \
                    f"diverged at step {step} probe {probe}"

    def test_v_blocking_ahead_latch_drops_on_regression(self):
        from stellar_core_tpu.scp import quorum as Q
        q = make_qset([nid(1), nid(2)], 2)   # any single node v-blocks
        qh = S.qset_hash(q)
        holder = make_qset([nid(9)], 1)
        idx = Q.StatementIndex()
        idx.note_statement(nid(1), 5, holder, b"h")
        assert Q.v_blocking_ahead(q, qh, idx, 4) is True
        assert idx.lookup(("vba", 4, qh)) is True       # latched
        idx.note_statement(nid(1), 2, holder, b"h")     # counter regression
        assert idx.lookup(("vba", 4, qh)) is None       # latch dropped
        assert Q.v_blocking_ahead(q, qh, idx, 4) \
            == self._scratch_ahead(q, idx, 4) is False

    def test_nomination_newer_registry_matches_xdr_walk(self):
        """_newer_by_summary (frozenset registries) vs the original
        XDR-walking _is_newer over randomized vote sets — including
        duplicate entries a hostile statement may carry, where raw-list
        totals and set sizes diverge."""
        import random
        from stellar_core_tpu.scp.nomination import _newer_by_summary

        def reference(new_votes, new_acc, old_votes, old_acc):
            # nomination.py's original _is_newer, verbatim semantics
            if not (set(old_votes) <= set(new_votes)):
                return False
            if not (set(old_acc) <= set(new_acc)):
                return False
            return (len(new_votes) + len(new_acc)
                    > len(old_votes) + len(old_acc))

        rng = random.Random(31)
        vals = [b"%d" % i for i in range(6)]
        for _ in range(500):
            old_votes = [rng.choice(vals)
                         for _ in range(rng.randrange(5))]
            old_acc = [rng.choice(vals) for _ in range(rng.randrange(4))]
            new_votes = [rng.choice(vals)
                         for _ in range(rng.randrange(5))]
            new_acc = [rng.choice(vals) for _ in range(rng.randrange(4))]
            got = _newer_by_summary(
                frozenset(new_votes), frozenset(new_acc),
                len(new_votes) + len(new_acc),
                (frozenset(old_votes), frozenset(old_acc)),
                len(old_votes) + len(old_acc))
            assert got == reference(new_votes, new_acc,
                                    old_votes, old_acc)
