"""Round-12 differentials: the 24/24 native op set + native live close.

Two families:

1. op-level differential fuzz for the 7 newly-ported frames (path
   payments over books AND pools, liquidity-pool deposit/withdraw edge
   rounding, CAP-33 sponsorship sandwiches incl. revoke on both arms):
   archives replayed through BOTH engines must produce bit-identical
   results, entry stores and bucket hashes — with ZERO per-checkpoint
   Python fallbacks (the round-12 acceptance criterion).

2. live close: `LedgerManager.close_ledger` through
   ledger/native_close.py — hash/result identity vs the Python close,
   green NATIVE_CLOSE_DIFFERENTIAL spot-checks, a forced C-side
   divergence fail-stopping with a crash bundle, and the
   degrade-to-Python path on engine error.
"""

import os
import random
import tempfile

import pytest

from stellar_core_tpu import xdr as X
from stellar_core_tpu.catchup.catchup import CatchupManager
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.history.archive import FileHistoryArchive
from stellar_core_tpu.history.manager import HistoryManager
from stellar_core_tpu.ledger.manager import LedgerManager
from stellar_core_tpu.ledger.native_apply import native_apply_available
from stellar_core_tpu import testutils as TU
from stellar_core_tpu.testutils import (TestAccount, build_tx,
                                        change_trust_op,
                                        change_trust_pool_op,
                                        create_account_op,
                                        liquidity_pool_deposit_op,
                                        liquidity_pool_withdraw_op,
                                        make_asset, manage_sell_offer_op,
                                        native_payment_op, network_id,
                                        path_payment_strict_receive_op,
                                        path_payment_strict_send_op,
                                        payment_op)
from stellar_core_tpu.transactions.offer_exchange import (asset_order,
                                                          pool_id_for)

pytestmark = pytest.mark.skipif(not native_apply_available(),
                                reason="_capply not built (make native)")

NID = network_id("native full-coverage network")
PASS = "native full-coverage network"


def _op(src_acct_id, body):
    return X.Operation(sourceAccount=TU._src(src_acct_id), body=body)


def _begin(sponsor_id, sponsored_id):
    return _op(sponsor_id, X.OperationBody.beginSponsoringFutureReservesOp(
        X.BeginSponsoringFutureReservesOp(sponsoredID=sponsored_id)))


def _end(src_id):
    return _op(src_id, X.OperationBody.endSponsoringFutureReserves())


def _revoke_key(src_id, key):
    return _op(src_id, X.OperationBody.revokeSponsorshipOp(
        X.RevokeSponsorshipOp.ledgerKey(key)))


def _revoke_signer(src_id, acct_id, signer_key):
    return _op(src_id, X.OperationBody.revokeSponsorshipOp(
        X.RevokeSponsorshipOp.signer(X.RevokeSponsorshipOpSigner(
            accountID=acct_id, signerKey=signer_key))))


def _archive(tmp, build_traffic, n_accounts=24):
    mgr = LedgerManager(NID, invariant_manager=None)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(tmp + "/archive")
    history = HistoryManager(mgr, PASS, [archive])
    root_sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.account_key_xdr(root_sk.public_key.ed25519))
    root = TestAccount(mgr, root_sk, e.data.value.seqNum)
    ct = [1_600_000_000]

    def close(frames):
        ct[0] += 5
        history.ledger_closed(mgr.close_ledger(frames, ct[0]))

    sks = [SecretKey(bytes([10 + i]) * 32) for i in range(n_accounts)]
    ops = [create_account_op(X.AccountID.ed25519(sk.public_key.ed25519),
                             10 ** 11) for sk in sks]
    close([root.tx(ops)])
    accounts = []
    for sk in sks:
        entry = mgr.root.get_entry(X.account_key_xdr(sk.public_key.ed25519))
        accounts.append(TestAccount(mgr, sk, entry.data.value.seqNum))
    build_traffic(close, accounts, root)
    while not history.published_checkpoints or \
            history.published_checkpoints[-1] != mgr.last_closed_ledger_seq:
        close([])
    return archive, mgr


def _assert_replays_agree_no_fallback(archive, mgr):
    """Both engines replay to the builder's hashes; the native replay must
    not forfeit a single checkpoint to the Python oracle."""
    cm_py = CatchupManager(NID, PASS, native=False)
    m_py = cm_py.catchup_complete(archive)
    cm_c = CatchupManager(NID, PASS, native=True)
    m_c = cm_c.catchup_complete(archive)
    assert m_py.lcl_hash == mgr.lcl_hash
    assert m_c.lcl_hash == mgr.lcl_hash
    assert m_c.bucket_list.hash() == m_py.bucket_list.hash()
    assert {k: e.to_xdr() for k, e in m_c.root._entries.items()} == \
        {k: e.to_xdr() for k, e in m_py.root._entries.items()}
    assert cm_c.stats.get("native_fallback_checkpoints", 0) == 0
    assert cm_c.stats.get("native_checkpoints", 0) > 0
    assert cm_c.stats.get("native_ledgers_applied", 0) > 0
    return cm_c


# ---------------------------------------------------------------------------
# 1. op-level differential fuzz for the 7 new frames


def test_all_24_ops_one_checkpoint_zero_fallbacks():
    """The acceptance shape: one archive whose traffic exercises path
    payments, pool ops AND sponsorship ops replays natively with zero
    fallbacks, bit-identical to Python."""
    def traffic(close, accounts, root):
        issuer = accounts[0]
        usd = make_asset("USD", issuer.account_id)
        eur = make_asset("EUR", issuer.account_id)
        xlm = X.Asset.native()
        close([a.tx([change_trust_op(usd), change_trust_op(eur)])
               for a in accounts[1:12]])
        close([issuer.tx([payment_op(a.account_id, usd, 5_000_000)
                          for a in accounts[1:8]])])
        close([issuer.tx([payment_op(a.account_id, eur, 5_000_000)
                          for a in accounts[1:8]])])
        # order books both ways + a passive offer
        close([accounts[1].tx([manage_sell_offer_op(usd, xlm, 100_000, 1, 2)]),
               accounts[2].tx([manage_sell_offer_op(eur, usd, 80_000, 3, 4)]),
               accounts[3].tx([manage_sell_offer_op(usd, eur, 70_000, 5, 4)]),
               accounts[4].tx([manage_sell_offer_op(xlm, usd, 120_000, 2, 1)])])
        # strict receive + strict send, single and multi hop
        close([accounts[5].tx([path_payment_strict_receive_op(
            xlm, 500_000, accounts[6].account_id, usd, 9_000, [])])])
        close([accounts[6].tx([path_payment_strict_send_op(
            usd, 5_000, accounts[7].account_id, eur, 1, [])])])
        close([accounts[5].tx([path_payment_strict_receive_op(
            xlm, 900_000, accounts[7].account_id, eur, 4_000, [usd])])])
        # pools: share lines, deposits (first + follow-up), pool-vs-book
        # path payments, withdraw
        a, b = (xlm, usd) if asset_order(xlm, usd) < 0 else (usd, xlm)
        pid = pool_id_for(a, b)
        close([accounts[1].tx([change_trust_pool_op(a, b)]),
               accounts[2].tx([change_trust_pool_op(a, b)])])
        close([accounts[1].tx([liquidity_pool_deposit_op(
            pid, 1_000_000, 2_000_000, (1, 4), (4, 1))])])
        close([accounts[2].tx([liquidity_pool_deposit_op(
            pid, 500_000, 500_000, (1, 10), (10, 1))])])
        close([accounts[5].tx([path_payment_strict_send_op(
            xlm, 50_000, accounts[6].account_id, usd, 1, [])])])
        close([accounts[5].tx([path_payment_strict_receive_op(
            xlm, 500_000, accounts[6].account_id, usd, 10_000, [])])])
        close([accounts[2].tx([liquidity_pool_withdraw_op(
            pid, 100_000, 0, 0)])])
        # sponsorship: sponsored zero-balance account + sponsored
        # trustline, then both revoke arms
        new_sk = SecretKey(bytes([200]) * 32)
        new_id = X.AccountID.ed25519(new_sk.public_key.ed25519)
        sponsor = accounts[8]
        close([build_tx(NID, sponsor.secret, sponsor.next_seq(), [
            _begin(sponsor.account_id, new_id),
            _op(sponsor.account_id, X.OperationBody.createAccountOp(
                X.CreateAccountOp(destination=new_id, startingBalance=0))),
            _end(new_id)], extra_signers=[new_sk])])
        close([build_tx(NID, sponsor.secret, sponsor.next_seq(), [
            _begin(sponsor.account_id, new_id),
            _op(new_id, X.OperationBody.changeTrustOp(X.ChangeTrustOp(
                line=X.ChangeTrustAsset(usd.switch, usd.value),
                limit=10 ** 10))),
            _end(new_id)], extra_signers=[new_sk])])
        tl_key = X.LedgerKey.trustLine(X.LedgerKeyTrustLine(
            accountID=new_id, asset=X.TrustLineAsset(usd.switch, usd.value)))
        # revoke while the owner cannot afford the reserve: LOW_RESERVE
        close([sponsor.tx([_revoke_key(sponsor.account_id, tl_key)])])
        # fund, then revoke succeeds (reserve moves back to the owner)
        close([accounts[9].tx([native_payment_op(new_id, 10 ** 10)])])
        close([sponsor.tx([_revoke_key(sponsor.account_id, tl_key)])])
        # signer arm: a sponsored signer, then revoked by its sponsor
        extra = SecretKey(bytes([201]) * 32)
        signer_key = X.SignerKey.ed25519(extra.public_key.ed25519)
        close([build_tx(NID, sponsor.secret, sponsor.next_seq(), [
            _begin(sponsor.account_id, accounts[10].account_id),
            _op(accounts[10].account_id, X.OperationBody.setOptionsOp(
                X.SetOptionsOp(signer=X.Signer(key=signer_key, weight=1)))),
            _end(accounts[10].account_id)],
            extra_signers=[accounts[10].secret])])
        close([sponsor.tx([_revoke_signer(
            sponsor.account_id, accounts[10].account_id, signer_key)])])
        # failure shapes ride along (recorded results must match too)
        close([accounts[9].tx([liquidity_pool_deposit_op(
            pid, 10, 10, (1, 1), (1, 1))])])          # NO_TRUST
        close([accounts[5].tx([path_payment_strict_receive_op(
            xlm, 1, accounts[6].account_id, usd, 1_000, [])])])  # OVER_MAX
        close([accounts[11].tx([_end(accounts[11].account_id)])])  # NOT_SPON
        # an unclosed sandwich fails the whole tx (txBAD_SPONSORSHIP)
        close([build_tx(NID, sponsor.secret, sponsor.next_seq(), [
            _begin(sponsor.account_id, accounts[10].account_id)])])

    with tempfile.TemporaryDirectory() as d:
        archive, mgr = _archive(d, traffic)
        _assert_replays_agree_no_fallback(archive, mgr)


def test_randomized_path_payment_and_pool_fuzz():
    """Deterministic fuzz over path-payment chains and pool
    deposit/withdraw edge rounding (first-deposit isqrt, ceil-div
    decrement branch, BAD_PRICE bounds, UNDER_MINIMUM) — every seed must
    replay bit-identically with zero fallbacks."""
    for seed in (101, 202, 303):
        rng = random.Random(seed)

        def traffic(close, accounts, root, rng=rng):
            issuer = accounts[0]
            xlm = X.Asset.native()
            usd = make_asset("USD", issuer.account_id)
            eur = make_asset("EUR", issuer.account_id)
            btc = make_asset("BTC", issuer.account_id)
            assets = [usd, eur, btc]
            close([a.tx([change_trust_op(x) for x in assets])
                   for a in accounts[1:14]])
            close([issuer.tx([payment_op(a.account_id, x,
                                         10 ** 7 + rng.randrange(10 ** 7))
                              for x in assets])
                   for a in accounts[1:10]])
            # seed books between every adjacent pair
            pairs = [(xlm, usd), (usd, eur), (eur, btc), (usd, btc)]
            frames = []
            for i, (s, b) in enumerate(pairs):
                seller = accounts[1 + i]
                frames.append(seller.tx([manage_sell_offer_op(
                    s, b, 50_000 + rng.randrange(100_000),
                    1 + rng.randrange(4), 1 + rng.randrange(4))]))
                frames.append(accounts[5 + i].tx([manage_sell_offer_op(
                    b, s, 50_000 + rng.randrange(100_000),
                    1 + rng.randrange(4), 1 + rng.randrange(4))]))
            close(frames)
            # pools over two canonical pairs
            pids = []
            for pa, pb in ((xlm, usd), (usd, eur)):
                a, b = (pa, pb) if asset_order(pa, pb) < 0 else (pb, pa)
                pid = pool_id_for(a, b)
                pids.append(pid)
                close([accounts[1].tx([change_trust_pool_op(a, b)]),
                       accounts[2].tx([change_trust_pool_op(a, b)])])
                close([accounts[1].tx([liquidity_pool_deposit_op(
                    pid, 1 + rng.randrange(10 ** 6),
                    1 + rng.randrange(10 ** 6),
                    (1, 1 + rng.randrange(8)),
                    (1 + rng.randrange(8), 1))])])
            # randomized hops + pool churn + edge-rounding deposits
            for _ in range(12):
                kind = rng.randrange(5)
                src = accounts[1 + rng.randrange(8)]
                dst = accounts[1 + rng.randrange(8)]
                if kind == 0:
                    path = rng.sample([usd, eur, btc], rng.randrange(3))
                    close([src.tx([path_payment_strict_receive_op(
                        xlm, 1 + rng.randrange(10 ** 6), dst.account_id,
                        rng.choice(assets), 1 + rng.randrange(5_000),
                        path)])])
                elif kind == 1:
                    path = rng.sample([usd, eur], rng.randrange(3))
                    close([src.tx([path_payment_strict_send_op(
                        rng.choice([xlm, usd]), 1 + rng.randrange(5_000),
                        dst.account_id, rng.choice(assets),
                        1 + rng.randrange(3), path)])])
                elif kind == 2:
                    close([accounts[1].tx([liquidity_pool_deposit_op(
                        rng.choice(pids), 1 + rng.randrange(1_000),
                        1 + rng.randrange(1_000),
                        (1, 1 + rng.randrange(10)),
                        (1 + rng.randrange(10), 1))])])
                elif kind == 3:
                    close([accounts[1].tx([liquidity_pool_withdraw_op(
                        rng.choice(pids), 1 + rng.randrange(500),
                        rng.randrange(2), rng.randrange(2))])])
                else:
                    close([src.tx([native_payment_op(
                        dst.account_id, 1 + rng.randrange(10 ** 6))])])

        with tempfile.TemporaryDirectory() as d:
            archive, mgr = _archive(d, traffic)
            _assert_replays_agree_no_fallback(archive, mgr)


def test_sponsorship_sandwich_fuzz():
    """Randomized sandwich shapes: sponsored accounts / trustlines /
    offers / data / signers, merges of sponsored accounts, revokes on
    both arms (transfer recipe incl. revoke-under-sandwich), failure
    sandwiches (RECURSIVE / ALREADY_SPONSORED / unclosed)."""
    for seed in (7, 77):
        rng = random.Random(seed)

        def traffic(close, accounts, root, rng=rng):
            issuer = accounts[0]
            usd = make_asset("USD", issuer.account_id)
            close([a.tx([change_trust_op(usd)]) for a in accounts[1:10]])
            sponsored_things = []
            for i in range(10):
                sponsor = accounts[1 + rng.randrange(6)]
                owner = accounts[1 + rng.randrange(6)]
                if sponsor.account_id == owner.account_id:
                    continue
                kind = rng.randrange(3)
                if kind == 0:
                    name = bytes([65 + i]) * (1 + rng.randrange(8))
                    inner = _op(owner.account_id,
                                X.OperationBody.manageDataOp(X.ManageDataOp(
                                    dataName=name, dataValue=b"v" * 4)))
                    key = X.LedgerKey.data(X.LedgerKeyData(
                        accountID=owner.account_id, dataName=name))
                elif kind == 1:
                    extra = SecretKey(bytes([120 + i]) * 32)
                    skey = X.SignerKey.ed25519(extra.public_key.ed25519)
                    inner = _op(owner.account_id,
                                X.OperationBody.setOptionsOp(X.SetOptionsOp(
                                    signer=X.Signer(key=skey, weight=1))))
                    key = ("signer", owner.account_id, skey)
                else:
                    inner = _op(owner.account_id,
                                X.OperationBody.manageSellOfferOp(
                                    X.ManageSellOfferOp(
                                        selling=X.Asset.native(),
                                        buying=usd,
                                        amount=1 + rng.randrange(1000),
                                        price=X.Price(n=1, d=1), offerID=0)))
                    key = ("offer", owner.account_id)
                close([build_tx(NID, sponsor.secret, sponsor.next_seq(), [
                    _begin(sponsor.account_id, owner.account_id),
                    inner,
                    _end(owner.account_id)], extra_signers=[owner.secret])])
                sponsored_things.append((sponsor, owner, key))
            # revoke roughly half of them (entry + signer arms)
            for sponsor, owner, key in sponsored_things[::2]:
                if isinstance(key, tuple) and key[0] == "signer":
                    close([sponsor.tx([_revoke_signer(
                        sponsor.account_id, key[1], key[2])])])
                elif isinstance(key, tuple) and key[0] == "offer":
                    continue    # offer ids are engine-assigned; skip
                else:
                    close([sponsor.tx([_revoke_key(
                        sponsor.account_id, key)])])
            # failure shapes: RECURSIVE + ALREADY_SPONSORED + merge of a
            # sandwich party
            s1, s2 = accounts[7], accounts[8]
            close([build_tx(NID, s1.secret, s1.next_seq(), [
                _begin(s1.account_id, s2.account_id),
                _begin(s2.account_id, accounts[9].account_id),  # RECURSIVE
                _end(s2.account_id)], extra_signers=[s2.secret])])
            close([build_tx(NID, s1.secret, s1.next_seq(), [
                _begin(s1.account_id, s2.account_id),
                _begin(s1.account_id, s2.account_id),  # ALREADY_SPONSORED
                _end(s2.account_id)], extra_signers=[s2.secret])])
            close([build_tx(NID, s1.secret, s1.next_seq(), [
                _begin(s1.account_id, s2.account_id),
                _op(s1.account_id,
                    X.OperationBody.destination(X.MuxedAccount.ed25519(
                        accounts[9].account_id.value))),  # merge: IS_SPONSOR
                _end(s2.account_id)], extra_signers=[s2.secret])])

        with tempfile.TemporaryDirectory() as d:
            archive, mgr = _archive(d, traffic)
            _assert_replays_agree_no_fallback(archive, mgr)


# ---------------------------------------------------------------------------
# 2. native live close


def _mk_close_pair(differential=0):
    """Two managers over the same genesis: one native-close, one Python."""
    def mk(native):
        mgr = LedgerManager(NID, invariant_manager=None)
        mgr.start_new_ledger()
        if native:
            assert mgr.attach_native_close(differential=differential)
        root_sk = mgr.root_account_secret()
        e = mgr.root.get_entry(X.account_key_xdr(root_sk.public_key.ed25519))
        return mgr, TestAccount(mgr, root_sk, e.data.value.seqNum)
    return mk(False), mk(True)


def _accounts(mgr, root, n=8):
    sks = [SecretKey(bytes([50 + i]) * 32) for i in range(n)]
    mgr.close_ledger([root.tx([create_account_op(
        X.AccountID.ed25519(sk.public_key.ed25519), 10 ** 11)
        for sk in sks])], 1_700_000_000)
    out = []
    for sk in sks:
        e = mgr.root.get_entry(X.account_key_xdr(sk.public_key.ed25519))
        out.append(TestAccount(mgr, sk, e.data.value.seqNum))
    return out


def _drive(mgr, root, seed=3, n_ledgers=12):
    accts = _accounts(mgr, root)
    rng = random.Random(seed)
    ct = 1_700_000_000
    out = []
    for _ in range(n_ledgers):
        ct += 5
        frames = [a.tx([native_payment_op(
            accts[rng.randrange(len(accts))].account_id,
            1000 + rng.randrange(10 ** 6))]) for a in accts[:5]]
        arts = mgr.close_ledger(frames, ct)
        out.append((mgr.lcl_hash, arts.result_entry.txResultSet.to_xdr()))
    return out


def test_live_close_identity_and_differential_green():
    (m_py, r_py), (m_c, r_c) = _mk_close_pair(differential=2)
    h_py = _drive(m_py, r_py)
    h_c = _drive(m_c, r_c)
    assert h_py == h_c
    closer = m_c.native_closer
    assert closer.closes > 0 and closer.degraded is None
    assert closer.differential_checks > 0      # spot-checks ran and passed
    # detach rebuilds the Python authority bit-identically
    m_c.detach_native_close()
    assert m_c.bucket_list.hash() == m_py.bucket_list.hash()
    assert {k: e.to_xdr() for k, e in m_c.root._entries.items()} == \
        {k: e.to_xdr() for k, e in m_py.root._entries.items()}


def test_live_close_mirrors_root_reads_between_closes():
    """tx-queue/admission read mgr.root between closes: the mirror must
    track every balance/seq change without an export."""
    (m_py, r_py), (m_c, r_c) = _mk_close_pair()
    _drive(m_py, r_py)
    _drive(m_c, r_c)
    # compare the LIVE mirror (no detach) against the Python manager
    assert {k: e.to_xdr() for k, e in m_c.root._entries.items()} == \
        {k: e.to_xdr() for k, e in m_py.root._entries.items()}
    assert m_c.lcl_header.to_xdr() == m_py.lcl_header.to_xdr()


def test_live_close_forced_divergence_fail_stops_with_bundle(tmp_path,
                                                             monkeypatch):
    from stellar_core_tpu.ledger.native_close import NativeCloseDivergence
    monkeypatch.setenv("STPU_CRASH_DIR", str(tmp_path))
    mgr = LedgerManager(NID, invariant_manager=None)
    mgr.start_new_ledger()
    assert mgr.attach_native_close(differential=1)
    root_sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.account_key_xdr(root_sk.public_key.ed25519))
    root = TestAccount(mgr, root_sk, e.data.value.seqNum)
    accts = _accounts(mgr, root)

    def corrupt(result):
        seq, lcl_hash, header_xdr, results_xdr, delta = result
        # flip the first tx's result code bytes: the spot-check must name
        # the tx in the crash bundle and fail-stop
        bad = bytearray(results_xdr)
        bad[-1] ^= 0xFF
        return seq, lcl_hash, header_xdr, bytes(bad), delta
    mgr.native_closer._corrupt_native_result_for_test = corrupt
    with pytest.raises(NativeCloseDivergence) as ei:
        mgr.close_ledger([accts[0].tx([native_payment_op(
            accts[1].account_id, 1234)])], 1_700_000_100)
    assert "ledger" in str(ei.value)
    bundles = list(tmp_path.glob("flight-*.json"))
    assert bundles, "divergence must write a crash bundle"
    assert any("NativeCloseDivergence" in b.read_text() for b in bundles)


def test_live_close_degrades_to_python_on_engine_error():
    mgr = LedgerManager(NID, invariant_manager=None)
    mgr.start_new_ledger()
    assert mgr.attach_native_close()
    root_sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.account_key_xdr(root_sk.public_key.ed25519))
    root = TestAccount(mgr, root_sk, e.data.value.seqNum)
    accts = _accounts(mgr, root)
    closer = mgr.native_closer
    degrade_reasons = []
    closer.on_degrade = degrade_reasons.append

    def boom(tx_rec, scp_xdr):
        raise RuntimeError("injected engine fault")
    closer.bridge.close_ledger = boom
    arts = mgr.close_ledger([accts[0].tx([native_payment_op(
        accts[1].account_id, 999)])], 1_700_000_200)
    assert arts is not None                   # the Python close covered it
    assert closer.degraded is not None
    assert degrade_reasons and "injected engine fault" in degrade_reasons[0]
    # later closes keep working (permanently on the Python engine)
    mgr.close_ledger([accts[2].tx([native_payment_op(
        accts[3].account_id, 888)])], 1_700_000_300)
    assert mgr.lcl_header.ledgerSeq >= 4


def test_live_close_empty_and_boundary_sync():
    """Empty tx sets close natively too, and a checkpoint boundary
    rebuilds the Python bucket list (history publishing reads it)."""
    from stellar_core_tpu.history.archive import is_checkpoint_boundary
    mgr = LedgerManager(NID, invariant_manager=None)
    mgr.start_new_ledger()
    assert mgr.attach_native_close()
    ct = 1_700_000_000
    while not is_checkpoint_boundary(mgr.last_closed_ledger_seq):
        ct += 5
        mgr.close_ledger([], ct)
    # the boundary sync happened: the PYTHON bucket list matches the
    # header even though authority stays in the engine
    assert mgr.bucket_list.hash() == mgr.lcl_header.bucketListHash
    assert mgr.native_closer.bridge.active


# ---------------------------------------------------------------------------
# 3. _native_build staleness guard


def test_stale_native_extension_fail_stops(tmp_path, monkeypatch):
    """A shipped .so older than its .c source must rebuild or raise —
    never silently load stale code."""
    from stellar_core_tpu import _native_build as nb

    src = tmp_path / "fake.c"
    src.write_bytes(b"// source\n")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    so = pkg / "_fake.cpython-310-x86_64-linux-gnu.so"
    so.write_bytes(b"\x7fELF stale")
    old = src.stat().st_mtime - 1000
    os.utime(so, (old, old))

    monkeypatch.setattr(nb, "_REPO", str(tmp_path))
    monkeypatch.setattr(nb, "_PKG", str(pkg))
    monkeypatch.setattr(nb, "_EXTENSIONS", {"_fake": "fake.c"})
    calls = []
    monkeypatch.setattr(nb, "ensure_native",
                        lambda quiet=True: calls.append(1) and False)
    with pytest.raises(nb.StaleNativeExtensionError):
        nb.require_fresh("_fake")
    assert calls, "require_fresh must attempt a rebuild first"
    # a FRESH .so passes without rebuilding
    now = src.stat().st_mtime + 1000
    os.utime(so, (now, now))
    calls.clear()
    assert nb.require_fresh("_fake") is True
    assert not calls
    # no shipped .so at all: the classic degrade-to-Python contract
    so.unlink()
    assert nb.require_fresh("_fake") is False
